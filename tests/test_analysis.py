"""Static collective analysis tests (ISSUE 5 tentpole).

Pins, in order of load-bearingness:

* the jaxpr walker extracts a correct ORDERED CollectiveTrace (axis
  names, dtypes, shapes, control-flow context) through
  ``pjit``/``scan``/``cond``/``while``/``shard_map`` nesting — including
  the ``_compat`` shard_map shim tier and the eager communicator tier
  (``XlaCommunicatorBase.allreduce_grad``'s bucketed path);
* the walker census AGREES with the HLO-text census on real compiled
  train steps (the transformer step here; ResNet-50 in
  test_comm_wire.py) — two independent counters verifying each other;
* the check catalog: deadlock lint on divergent ``cond`` arms, mesh
  axis audit, narrowing-cast wire audit (flags the legacy per-leaf
  cast, exempts the comm_wire codecs);
* budget pins enforced from the analyzer for the ZeRO, expert-parallel
  MoE, and pipeline paths (ResNet-50's pin lives in test_comm_wire.py);
* the divergence guard: ``trace_agreement`` raises the non-recoverable
  ``CollectiveTraceMismatchError`` on hash mismatch, and
  ``build_train_step`` wires it into the first multi-process dispatch
  (the real 2-process version is mp_worker.py's ``trace_divergence``).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

import chainermn_tpu as cmn
from chainermn_tpu.analysis import (
    BUDGETS,
    CollectiveBudgetError,
    CollectiveTraceMismatchError,
    assert_census_agreement,
    assert_within_budget,
    budget_for,
    check_axes,
    check_deadlocks,
    check_wire,
    enforce,
    hlo_census,
    trace_agreement,
    trace_collectives,
)
from chainermn_tpu.optimizers import build_train_step


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("tpu", devices=devices8)


def _smap(fn, mesh, n_in=1, out_spec=None):
    spec = P("mn")
    return jax.shard_map(
        fn, mesh=mesh, in_specs=tuple([spec] * n_in),
        out_specs=spec if out_spec is None else out_spec,
        check_vma=False,
    )


# ----------------------------------------------------------------------
# walker: ordering, metadata, nesting
# ----------------------------------------------------------------------
class TestWalker:
    def test_ordered_records_with_axes_dtypes_shapes(self, mesh8):
        def f(x):
            a = lax.psum(x, "mn")
            b = lax.pmax(x.astype(jnp.float32), "mn")
            g = lax.all_gather(x, "mn", axis=0, tiled=True)
            s = lax.psum_scatter(g, "mn", scatter_dimension=0, tiled=True)
            p = lax.ppermute(
                x, "mn", [(i, (i + 1) % 8) for i in range(8)]
            )
            return a + b.astype(x.dtype) + s[:1] * 0 + p

        tr = trace_collectives(
            _smap(f, mesh8), jnp.zeros((8, 4), jnp.bfloat16)
        )
        prims = [r.primitive for r in tr]
        # lax.psum_scatter binds the reduce_scatter primitive
        assert prims == [
            "psum", "pmax", "all_gather", "reduce_scatter", "ppermute"
        ]
        assert [r.cls for r in tr] == [
            "all_reduce", "all_reduce", "all_gather", "reduce_scatter",
            "collective_permute",
        ]
        assert all(r.axes == ("mn",) for r in tr)
        assert tr.records[0].dtypes == ("bfloat16",)
        assert tr.records[1].dtypes == ("float32",)
        # per-shard operand shapes: (1, 4) into the psum, (8, 4) into
        # the reduce_scatter (it consumes the gathered block)
        assert tr.records[0].shapes == ((1, 4),)
        assert tr.records[3].shapes == ((8, 4),)
        # ppermute's permutation is part of the program identity
        assert "perm=" in tr.records[4].detail
        assert tr.axis_names() == ("mn",)

    def test_pmean_is_one_psum(self, mesh8):
        tr = trace_collectives(
            _smap(lambda x: lax.pmean(x, "mn"), mesh8), jnp.zeros((8, 4))
        )
        assert [r.primitive for r in tr] == ["psum"]
        assert tr.census() == {"all_reduce": 1}

    def test_multi_operand_psum_is_one_record(self, mesh8):
        def f(x):
            a, b = lax.psum((x, x * 2), "mn")
            return a + b

        tr = trace_collectives(_smap(f, mesh8), jnp.zeros((8, 4)))
        # one variadic eqn -> ONE record carrying both operands (XLA
        # lowers it to one variadic all-reduce, so census agreement
        # depends on this)
        assert len(tr) == 1
        assert tr.records[0].dtypes == ("float32", "float32")

    def test_nested_scan_cond_pjit_contexts(self, mesh8):
        def inner(c):
            return lax.psum(c, "mn")

        def f(x):
            def body(c, _):
                c = jax.jit(inner)(c)
                c = lax.cond(
                    c.sum() > 0,
                    lambda y: lax.pmax(y, "mn"),
                    lambda y: y * 2.0,
                    c,
                )
                return c, None

            out, _ = lax.scan(body, x, None, length=3)
            return out

        tr = trace_collectives(_smap(f, mesh8), jnp.zeros((8, 4)))
        assert [r.primitive for r in tr] == ["psum", "pmax"]
        psum_rec, pmax_rec = tr.records
        assert psum_rec.context == ("shard_map", "scan", "pjit")
        assert pmax_rec.context[:2] == ("shard_map", "scan")
        assert pmax_rec.context[2].startswith("cond#1[")
        assert pmax_rec.in_cond() and not psum_rec.in_cond()

    def test_while_loop_context(self, mesh8):
        def f(x):
            def wcond(c):
                return c[1] < 3

            def wbody(c):
                return (lax.psum(c[0], "mn"), c[1] + 1)

            out, _ = lax.while_loop(wcond, wbody, (x, 0))
            return out

        tr = trace_collectives(_smap(f, mesh8), jnp.zeros((8, 4)))
        assert len(tr) == 1
        assert tr.records[0].context == ("shard_map", "while/body")

    def test_shard_map_shim_tier(self, mesh8):
        """``jax.shard_map`` here is the _compat shim on old jax (it
        forwards to jax.experimental.shard_map) and the native API on
        current jax — the walker must descend the shard_map eqn either
        way, and the trace hash must not depend on which tier traced."""
        from chainermn_tpu import _compat

        sm = jax.shard_map(
            lambda x: lax.pmean(x, "mn"), mesh=mesh8,
            in_specs=(P("mn"),), out_specs=P("mn"), check_vma=False,
        )
        tr = trace_collectives(sm, jnp.zeros((8, 4)))
        assert tr.census() == {"all_reduce": 1}
        assert tr.records[0].context[0] == "shard_map"
        assert isinstance(_compat.OLD_SHARD_MAP, bool)  # shim resolved

    def test_trace_hash_is_value_independent(self, mesh8):
        fn = _smap(lambda x: lax.psum(x, "mn"), mesh8)
        h1 = trace_collectives(fn, jnp.zeros((8, 4))).trace_hash()
        h2 = trace_collectives(fn, jnp.ones((8, 4)) * 7).trace_hash()
        h3 = trace_collectives(
            fn, jax.ShapeDtypeStruct((8, 4), jnp.float32)
        ).trace_hash()
        assert h1 == h2 == h3

    def test_trace_hash_changes_with_program(self, mesh8):
        h1 = trace_collectives(
            _smap(lambda x: lax.psum(x, "mn"), mesh8), jnp.zeros((8, 4))
        ).trace_hash()
        h2 = trace_collectives(
            _smap(lambda x: lax.psum(lax.psum(x, "mn"), "mn"), mesh8),
            jnp.zeros((8, 4)),
        ).trace_hash()
        h3 = trace_collectives(
            _smap(lambda x: lax.pmax(x, "mn"), mesh8), jnp.zeros((8, 4))
        ).trace_hash()
        assert len({h1, h2, h3}) == 3

    def test_canonical_excludes_source_locations(self, mesh8):
        # two textually-distinct call sites, same program -> same hash
        def f1(x):
            return lax.psum(x, "mn")

        def f2(x):
            return lax.psum(x, "mn")  # different line on purpose

        t1 = trace_collectives(_smap(f1, mesh8), jnp.zeros((8, 4)))
        t2 = trace_collectives(_smap(f2, mesh8), jnp.zeros((8, 4)))
        assert t1.trace_hash() == t2.trace_hash()
        # ... while the records still carry sources for diagnostics
        assert t1.records[0].source and "test_analysis" in t1.records[0].source


# ----------------------------------------------------------------------
# deadlock lint
# ----------------------------------------------------------------------
class TestDeadlockLint:
    def _trace_cond(self, mesh8, true_fn, false_fn):
        def f(x):
            return lax.cond(x.sum() > 0, true_fn, false_fn, x)

        return trace_collectives(_smap(f, mesh8), jnp.zeros((8, 4)))

    def test_divergent_branches_are_an_error(self, mesh8):
        tr = self._trace_cond(
            mesh8,
            lambda y: lax.psum(y, "mn"),
            lambda y: y * 2.0,
        )
        findings = check_deadlocks(tr)
        assert len(findings) == 1
        assert findings[0].severity == "error"
        assert "different collective sequences" in findings[0].message
        assert tr.cond_reports[0].diverges

    def test_lockstep_branches_warn_only(self, mesh8):
        tr = self._trace_cond(
            mesh8,
            lambda y: lax.psum(y, "mn") * 2.0,
            lambda y: lax.psum(y, "mn") + 1.0,
        )
        findings = check_deadlocks(tr)
        assert [f.severity for f in findings] == ["warning"]
        assert not tr.cond_reports[0].diverges

    def test_identical_nested_cond_arms_are_lockstep(self, mesh8):
        """Regression: the walk-global cond counter gives arm 0's inner
        cond a different id (cond#2) than arm 1's identical inner cond
        (cond#3); the branch comparison must strip the ids, or every
        lockstep program with nested conds false-positives as a
        deadlock."""
        def nested(y):
            return lax.cond(
                y.sum() > 1.0,
                lambda z: lax.psum(z, "mn"),
                lambda z: lax.psum(z, "mn") * 2.0,
                y,
            )

        tr = self._trace_cond(mesh8, nested, nested)
        outer = [r for r in tr.cond_reports if r.cond_id == "cond#1"]
        assert outer and not outer[0].diverges
        assert all(f.severity == "warning" for f in check_deadlocks(tr))

    def test_divergent_nested_cond_arms_still_error(self, mesh8):
        def n_psum(y):
            return lax.cond(
                y.sum() > 1.0,
                lambda z: lax.psum(z, "mn"),
                lambda z: lax.psum(z, "mn") * 2.0,
                y,
            )

        def n_pmax(y):
            return lax.cond(
                y.sum() > 1.0,
                lambda z: lax.pmax(z, "mn"),
                lambda z: lax.pmax(z, "mn") * 2.0,
                y,
            )

        tr = self._trace_cond(mesh8, n_psum, n_pmax)
        outer = [r for r in tr.cond_reports if r.cond_id == "cond#1"]
        assert outer[0].diverges
        assert any(f.severity == "error" for f in check_deadlocks(tr))

    def test_collective_free_cond_is_clean(self, mesh8):
        tr = self._trace_cond(
            mesh8, lambda y: y * 2.0, lambda y: y + 1.0
        )
        assert check_deadlocks(tr) == []
        # the report still exists (branch structure was analyzed), it
        # just has nothing to flag
        assert not tr.cond_reports[0].has_collectives


# ----------------------------------------------------------------------
# deadlock lint: while bodies (ISSUE 6 satellite — PR 4 only compared
# cond arms)
# ----------------------------------------------------------------------
class TestWhileDeadlockLint:
    def _findings(self, mesh8, fn):
        tr = trace_collectives(_smap(fn, mesh8), jnp.zeros((8, 4)))
        return tr, check_deadlocks(tr)

    def test_counter_while_with_collective_warns(self, mesh8):
        """The fori shape: predicate reads a carry slot the body
        advances by a constant — trip count rank-uniform, so the
        collective inside gets the lockstep-cond treatment (warning)."""
        def f(x):
            def wbody(c):
                return (lax.psum(c[0], "mn"), c[1] + 1)

            out, _ = lax.while_loop(lambda c: c[1] < 3, wbody, (x, 0))
            return out

        tr, findings = self._findings(mesh8, f)
        assert tr.while_reports[0].counter_only_predicate
        assert tr.while_reports[0].trip_count_agreed
        assert [f.severity for f in findings] == ["warning"]
        assert "counter-only" in findings[0].message

    def test_data_dependent_while_with_collective_errors(self, mesh8):
        """Predicate reads a data-carrying slot: rank-divergent trip
        counts issue divergent collective sequences — error."""
        def f(x):
            def wbody(c):
                return (lax.psum(c[0], "mn") * 0.5, c[1] + 1)

            out, _ = lax.while_loop(
                lambda c: c[0].sum() < 3.0, wbody, (x, 0)
            )
            return out

        tr, findings = self._findings(mesh8, f)
        assert not tr.while_reports[0].trip_count_agreed
        assert [f.severity for f in findings] == ["error"]
        assert "data-dependent while" in findings[0].message

    def test_reduction_agreed_predicate_warns(self, mesh8):
        """The convergence-loop shape: the predicate itself is computed
        through a psum, so every rank agrees to continue or exit —
        aligned today, warning not error."""
        def f(x):
            def wbody(c):
                return (c[0] * 0.5, c[1] + 1)

            out, _ = lax.while_loop(
                lambda c: lax.psum(c[0].sum(), "mn") > 1.0, wbody,
                (x, 0),
            )
            return out

        tr, findings = self._findings(mesh8, f)
        rep = tr.while_reports[0]
        assert rep.cond_has_reduction and rep.trip_count_agreed
        assert [f.severity for f in findings] == ["warning"]
        assert "cross-rank reduction" in findings[0].message

    def test_collective_free_while_is_clean(self, mesh8):
        def f(x):
            def wbody(c):
                return (c[0] * 0.5, c[1] + 1)

            out, _ = lax.while_loop(
                lambda c: c[0].sum() < 3.0, wbody, (x, 0)
            )
            return out

        tr, findings = self._findings(mesh8, f)
        assert not tr.while_reports[0].has_collectives
        assert findings == []


# ----------------------------------------------------------------------
# axis audit
# ----------------------------------------------------------------------
class TestAxisAudit:
    def test_unknown_axis_flagged(self, comm, mesh8):
        tr = trace_collectives(
            _smap(lambda x: lax.psum(x, "mn"), mesh8), jnp.zeros((8, 4))
        )
        assert check_axes(tr, comm.axis_names) == []
        findings = check_axes(tr, ("mn_inter", "mn_intra"))
        assert len(findings) == 1
        assert "unknown axis mn" in findings[0].message

    def test_bare_string_axis_name_not_split_into_chars(self, mesh8):
        # axis_name attributes are often plain strings; "mn" must mean
        # the axis, not the set {'m', 'n'}
        tr = trace_collectives(
            _smap(lambda x: lax.psum(x, "mn"), mesh8), jnp.zeros((8, 4))
        )
        assert check_axes(tr, "mn") == []
        assert check_axes(tr, "mn_other") != []

    def test_hierarchical_step_passes_its_own_mesh(self, devices8):
        c = cmn.create_communicator("hierarchical", devices=devices8)
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), c)
        params = {"w": jnp.zeros((4,))}

        def loss(p, b):
            return 0.5 * jnp.sum((p["w"] - b.mean(axis=0)) ** 2)

        step = build_train_step(c, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(jnp.zeros((8, 4)), step.batch_sharding)
        tr = step.collective_trace(p, o, batch)
        assert len(tr) >= 2  # grad bucket(s) + loss pmean
        assert check_axes(tr, c.axis_names) == []
        # and the flat communicator's axis set would (correctly) fail
        assert check_axes(tr, ("mn",)) != []


# ----------------------------------------------------------------------
# wire audit
# ----------------------------------------------------------------------
class TestWireAudit:
    def _step_trace(self, devices8, wire):
        c = cmn.create_communicator(
            "tpu", devices=devices8, allreduce_grad_dtype=jnp.bfloat16
        )
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), c, wire=wire)
        params = {"w": jnp.zeros((8,)), "v": jnp.zeros((3,))}

        def loss(p, b):
            m = b.mean(axis=0)
            return 0.5 * jnp.sum((p["w"] - m[:8]) ** 2) + 0.5 * jnp.sum(
                (p["v"] - m[8:]) ** 2
            )

        step = build_train_step(c, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(jnp.zeros((8, 11)), step.batch_sharding)
        return step.collective_trace(p, o, batch)

    def test_legacy_per_leaf_cast_is_flagged(self, devices8):
        tr = self._step_trace(devices8, "per_leaf")
        findings = check_wire(tr)
        assert findings, "per-leaf bf16 cast must be flagged"
        assert all("optimizers.py" in (f.source or "") for f in findings)
        assert all("bfloat16" in f.message for f in findings)

    def test_comm_wire_codec_is_exempt(self, devices8):
        tr = self._step_trace(devices8, "auto")  # bf16 codec, bucketed
        # the narrowing cast EXISTS (it's the wire codec)...
        assert tr.narrowing_casts, "bf16 codec must narrow on the wire"
        # ...but it lives in comm_wire, the sanctioned place
        assert check_wire(tr) == []

    def test_uncompressed_wire_has_no_narrowing(self, comm):
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        params = {"w": jnp.zeros((4,))}

        def loss(p, b):
            return 0.5 * jnp.sum((p["w"] - b.mean(axis=0)) ** 2)

        step = build_train_step(comm, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(jnp.zeros((8, 4)), step.batch_sharding)
        tr = step.collective_trace(p, o, batch)
        assert tr.narrowing_casts == ()
        assert check_wire(tr) == []


# ----------------------------------------------------------------------
# census agreement + budget pins (transformer / ZeRO / MoE / pipeline)
# ----------------------------------------------------------------------
class TestTransformerCensus:
    def test_transformer_step_analyzer_agrees_with_hlo(self, comm):
        """Acceptance: the walker and the HLO text count the same
        all-reduces on the transformer train step, and the step stays
        within the pinned wire budget."""
        from chainermn_tpu.models.transformer import TransformerLM, lm_loss

        model = TransformerLM(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2,
            max_len=64, dtype=jnp.float32,
        )
        toks = jnp.zeros((8, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks[:1])

        def loss_fn(p, b):
            return lm_loss(model.apply(p, b), b)

        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(toks, step.batch_sharding)
        tr = step.collective_trace(p, o, batch)
        txt = step.get_jitted(p, o).lower(p, o, batch).as_text()
        agreed = assert_census_agreement(tr, txt)
        assert agreed["all_reduce"] >= 2  # bucket(s) + loss pmean
        enforce("transformer_train_step", tr)


class TestBudgets:
    def test_zero_step_within_reduce_scatter_budget(self, comm):
        params = {"w": jnp.ones((8,)) * 0.3, "v": jnp.ones((16,)) * -0.2}

        def loss(p, b):
            m = b.mean(axis=0)
            return 0.5 * jnp.sum((p["w"] - m[:8]) ** 2) + 0.5 * jnp.sum(
                (p["v"] - m[8:]) ** 2
            )

        opt = cmn.create_multi_node_optimizer(
            optax.adam(0.1), comm, zero_redundancy=True
        )
        step = build_train_step(comm, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(jnp.zeros((8, 24)), step.batch_sharding)
        tr = step.collective_trace(p, o, batch)
        census = enforce("zero_train_step", tr)
        # the ZeRO shape: gradients go DOWN via reduce_scatter, updates
        # come BACK via all_gather, and only the loss pmean all-reduces
        assert census["reduce_scatter"] >= 1
        assert census["all_gather"] >= 1
        assert census["all_reduce"] == 1

    def test_ep_moe_layer_exactly_two_all_to_all(self, comm, mesh8):
        from chainermn_tpu.parallel.expert_parallel import (
            expert_parallel_moe,
            mlp_experts,
        )

        d, dff, E = 8, 16, 8
        router = jnp.zeros((d, E))
        w1 = jnp.zeros((E // 8, d, dff))
        w2 = jnp.zeros((E // 8, dff, d))

        def moe(x):
            return expert_parallel_moe(
                x, router, mlp_experts(w1, w2), "mn", E, k=2
            )[0]

        tr = trace_collectives(
            _smap(moe, mesh8, out_spec=P()), jnp.zeros((16, d))
        )
        census = enforce("ep_moe_layer", tr)
        assert census["all_to_all"] == 2  # dispatch + return, no more

    def test_pipeline_forward_one_permute_one_psum(self, comm, mesh8):
        from chainermn_tpu.parallel.pipeline import gpipe

        def stage_fn(sp, h):
            return jnp.tanh(h @ sp)

        def fwd(sp, xm):
            y = gpipe(stage_fn, sp[0], xm, "mn")
            is_last = lax.axis_index("mn") == lax.axis_size("mn") - 1
            return lax.psum(
                jnp.where(is_last, y.sum(), 0.0), "mn"
            )

        tr = trace_collectives(
            jax.shard_map(
                fwd, mesh=mesh8, in_specs=(P("mn"), P()),
                out_specs=P(), check_vma=False,
            ),
            jnp.zeros((8, 4, 4)),  # per-stage params, stacked
            jnp.zeros((4, 2, 4)),  # (n_micro, micro_batch, d)
        )
        census = enforce("pipeline_forward", tr)
        # the ring edge appears ONCE (inside the scan body), exactly as
        # it appears once in the lowered while-loop body
        assert census["collective_permute"] == 1
        assert tr.records[0].context[-1] == "scan"

    def test_pipeline_train_step_backward_permute_pinned(
        self, comm, mesh8
    ):
        """ISSUE 6 satellite: only the FORWARD ppermute was pinned —
        the transposed reverse-ring permute that autodiff generates was
        unguarded.  The full train step traces to exactly 2
        collective_permute (forward edge + transposed edge, each once
        inside its scan body) and 2 all_reduce (loss psum + its
        transpose), pinned by ``pipeline_train_step``."""
        from chainermn_tpu.parallel.pipeline import gpipe

        def stage_fn(sp, h):
            return jnp.tanh(h @ sp)

        def fwd(sp, xm):
            y = gpipe(stage_fn, sp[0], xm, "mn")
            is_last = lax.axis_index("mn") == lax.axis_size("mn") - 1
            return lax.psum(jnp.where(is_last, y.sum(), 0.0), "mn")

        def train(sp, xm):
            return jax.grad(fwd)(sp, xm)

        tr = trace_collectives(
            jax.shard_map(
                train, mesh=mesh8, in_specs=(P("mn"), P()),
                out_specs=P("mn"), check_vma=False,
            ),
            jnp.zeros((8, 4, 4)),
            jnp.zeros((4, 2, 4)),
        )
        census = enforce("pipeline_train_step", tr)
        assert census["collective_permute"] == 2
        # both ring edges live inside their scan bodies (fwd + bwd)
        permutes = [r for r in tr if r.cls == "collective_permute"]
        assert all("scan" in r.context for r in permutes)
        # the reverse permute is the transpose of the forward one
        assert permutes[0].detail != permutes[1].detail

    def test_budget_violation_raises_with_census(self, comm):
        from chainermn_tpu.models import MLP

        model = MLP(n_units=50)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))
        n_leaves = len(jax.tree_util.tree_leaves(params))
        assert n_leaves > 4

        def loss_fn(p, b):
            x, y = b
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x), y
            ).mean()

        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), comm, wire="per_leaf"
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = (
            jax.device_put(jnp.zeros((8, 28, 28)), step.batch_sharding),
            jax.device_put(jnp.zeros((8,), jnp.int32),
                           step.batch_sharding),
        )
        tr = step.collective_trace(p, o, batch)
        assert tr.count("all_reduce") == n_leaves + 1  # the leaf storm
        with pytest.raises(CollectiveBudgetError, match="all_reduce"):
            assert_within_budget(tr, {"all_reduce": n_leaves // 2},
                                 name="per_leaf_storm")

    def test_budget_registry(self):
        assert budget_for("resnet50_train_step") == {"all_reduce": 8}
        assert "zero_train_step" in BUDGETS
        with pytest.raises(KeyError, match="no pinned budget"):
            budget_for("nonexistent_path")


# ----------------------------------------------------------------------
# eager communicator tier
# ----------------------------------------------------------------------
class TestEagerTier:
    def test_allreduce_grad_bucketed_path_traces(self, comm):
        """Satellite: the eager ``XlaCommunicatorBase.allreduce_grad``
        traces end to end — the walker descends the cached-jit (pjit)
        dispatch and finds ONE psum per wire bucket, which is the
        bucketed-launch contract of PR 3."""
        from chainermn_tpu import comm_wire as cw

        rng = np.random.RandomState(0)
        grads = {
            "w": jnp.asarray(rng.randn(comm.size, 3, 4), jnp.float32),
            "b": jnp.asarray(rng.randn(comm.size, 5), jnp.float32),
        }
        per_rank = [l[0] for l in jax.tree_util.tree_leaves(grads)]
        plan = cw.make_plan(per_rank)

        tr = trace_collectives(
            lambda t: comm.allreduce_grad(t), grads, label="allreduce_grad"
        )
        assert tr.count("all_reduce") == plan.n_buckets
        assert all(r.context and r.context[0] == "pjit" for r in tr)

    def test_eager_cast_tier_is_wire_audit_visible(self, devices8):
        # the bf16 eager tier narrows OUTSIDE comm_wire codecs — the
        # audit must see it (it is the eager analogue of the per-leaf
        # legacy path, kept for reference parity)
        c = cmn.create_communicator(
            "tpu", devices=devices8, allreduce_grad_dtype=jnp.bfloat16
        )
        grads = {"w": jnp.zeros((8, 3))}
        tr = trace_collectives(lambda t: c.allreduce_grad(t), grads)
        assert check_wire(tr), "eager cast tier should be flagged"


# ----------------------------------------------------------------------
# divergence guard
# ----------------------------------------------------------------------
class _FakeComm:
    """Host-control-plane stub: only what trace_agreement touches."""

    def __init__(self, peers):
        self._peers = peers

    def allgather_obj(self, h):
        return [h] + list(self._peers(h))


class TestTraceAgreement:
    def _trace(self, mesh8):
        return trace_collectives(
            _smap(lambda x: lax.psum(x, "mn"), mesh8), jnp.zeros((8, 4))
        )

    def test_agreement_returns_hash(self, mesh8, comm):
        tr = self._trace(mesh8)
        # real communicator (single process: world of one agrees)
        assert trace_agreement(comm, tr) == tr.trace_hash()
        # fake 2-process world that agrees
        fake = _FakeComm(lambda h: [h])
        assert trace_agreement(fake, tr) == tr.trace_hash()

    def test_mismatch_raises_nonrecoverable(self, mesh8):
        tr = self._trace(mesh8)
        fake = _FakeComm(lambda h: ["a-divergent-trace-hash"])
        with pytest.raises(CollectiveTraceMismatchError,
                           match="trace hash mismatch") as ei:
            trace_agreement(fake, tr)
        assert ei.value.recoverable is False
        assert "trace_agreement" in ei.value.site

    def test_truncated_exchange_retries_in_lockstep(self, mesh8, comm):
        from chainermn_tpu.resilience.fault_injection import (
            FaultSpec,
            inject_faults,
        )

        tr = self._trace(mesh8)
        with inject_faults(
            [FaultSpec("obj_store.exchange", "truncate", at=[1],
                       truncate_to=4)]
        ) as inj:
            assert trace_agreement(comm, tr) == tr.trace_hash()
        assert inj.log.counts.get("fault_injected", 0) >= 1


class _MultiProcProxy:
    """Wrap a real single-process communicator so build_train_step sees
    a 2-process world whose trace exchange we script — the
    single-controller half of the mp ``trace_divergence`` scenario."""

    def __init__(self, real, exchange):
        object.__setattr__(self, "_real", real)
        object.__setattr__(self, "_exchange", exchange)

    def __getattr__(self, name):
        if name == "process_count":
            return 2
        if name == "allgather_obj":
            return self._exchange
        return getattr(object.__getattribute__(self, "_real"), name)


class TestGuardWiring:
    def _pieces(self, comm, proxy):
        # the optimizer keeps the REAL comm (its init-time plan guard
        # would otherwise also exchange through the scripted proxy)
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        params = {"w": jnp.zeros((4,))}

        def loss(p, b):
            return 0.5 * jnp.sum((p["w"] - b.mean(axis=0)) ** 2)

        step = build_train_step(proxy, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(jnp.zeros((8, 4)), step.batch_sharding)
        return step, p, o, batch

    def test_first_dispatch_guards_in_multiprocess_world(self, comm):
        proxy = _MultiProcProxy(comm, lambda h: [h, "divergent-peer"])
        step, p, o, batch = self._pieces(comm, proxy)
        with pytest.raises(CollectiveTraceMismatchError):
            step(p, o, batch)
        # the guard fired ONCE, before dispatch; after the (fatal)
        # mismatch a retry would re-raise from the exchange only if
        # re-armed — it is not, matching plan_agreement's fail-fast
        out = step(p, o, batch)  # agreement not retried; step runs
        assert np.isfinite(float(out[2]["loss"]))

    def test_agreeing_world_proceeds(self, comm):
        proxy = _MultiProcProxy(comm, lambda h: [h, h])
        step, p, o, batch = self._pieces(comm, proxy)
        p2, _, m = step(p, o, batch)
        assert np.isfinite(float(m["loss"]))

    def test_new_program_variant_reguards(self, comm):
        """Regression: the guard is per compiled-program variant, not
        once per step object — a new batch shape (or params/opt_state
        structure) retraces into a potentially different collective
        sequence and must be re-verified before it dispatches."""
        exchanges = []

        def agreeing(h):
            exchanges.append(h)
            return [h, h]

        proxy = _MultiProcProxy(comm, agreeing)
        step, p, o, batch = self._pieces(comm, proxy)
        step(p, o, batch)
        step(p, o, batch)  # same variant: verified once
        assert len(exchanges) == 1
        batch2 = jax.device_put(jnp.zeros((16, 4)), step.batch_sharding)
        step(p, o, batch2)  # new batch shape: a NEW program — re-guard
        assert len(exchanges) == 2
        step(p, o, batch2)
        assert len(exchanges) == 2
        # same pytree STRUCTURE, different leaf avals (resized param —
        # (2, 4) still broadcasts against the (B, 4) batch): jit
        # retraces — the bucket plan is a function of shapes, so the
        # collective sequence can change — and must be re-guarded
        params2 = {"w": jnp.zeros((2, 4))}
        opt2 = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        p2, o2 = step.place(params2, opt2.init(params2))
        step(p2, o2, batch2)
        assert len(exchanges) == 3

    def test_transient_exchange_failure_rearms_guard(self, comm):
        """Regression: a transiently-failed hash exchange must NOT
        disarm the guard — an auto-resumed run re-verifies instead of
        skipping straight into the potential deadlock.  Only success
        and a fatal mismatch disarm."""
        from chainermn_tpu.resilience.errors import TransientCommError

        attempts = []

        def flaky(h):
            attempts.append(h)
            if len(attempts) <= 4:  # exhaust the whole retry budget
                raise TransientCommError("injected", site="test")
            return [h, h]

        proxy = _MultiProcProxy(comm, flaky)
        step, p, o, batch = self._pieces(comm, proxy)
        with pytest.raises(TransientCommError):
            step(p, o, batch)
        assert len(attempts) == 4  # the internal retry budget, spent
        # still armed: the next call re-exchanges, agrees, and runs
        _, _, m = step(p, o, batch)
        assert np.isfinite(float(m["loss"]))
        assert len(attempts) == 5
        # disarmed after success: no further exchanges
        step(p, o, batch)
        assert len(attempts) == 5

    def test_env_opt_out(self, comm, monkeypatch):
        monkeypatch.setenv("CHAINERMN_TPU_TRACE_GUARD", "0")
        proxy = _MultiProcProxy(comm, lambda h: [h, "divergent-peer"])
        step, p, o, batch = self._pieces(comm, proxy)
        _, _, m = step(p, o, batch)  # guard disabled: no raise
        assert np.isfinite(float(m["loss"]))

    def test_single_process_never_exchanges(self, comm):
        calls = []

        class _Counting(_MultiProcProxy):
            def __getattr__(self, name):
                if name == "process_count":
                    return 1  # single-controller world
                if name == "allgather_obj":
                    def ag(h):
                        calls.append(h)
                        return [h]

                    return ag
                return getattr(
                    object.__getattribute__(self, "_real"), name
                )

        proxy = _Counting(comm, None)
        step, p, o, batch = self._pieces(comm, proxy)
        step(p, o, batch)
        assert calls == []  # nothing to disagree with, no exchange

    def test_explicit_verify_returns_hash(self, comm):
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        params = {"w": jnp.zeros((4,))}

        def loss(p, b):
            return 0.5 * jnp.sum((p["w"] - b.mean(axis=0)) ** 2)

        step = build_train_step(comm, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(jnp.zeros((8, 4)), step.batch_sharding)
        h = step.verify_collective_trace(p, o, batch)
        assert h == step.collective_trace(p, o, batch).trace_hash()


# ----------------------------------------------------------------------
# hlo census unit behavior
# ----------------------------------------------------------------------
class TestHloCensus:
    def test_stablehlo_spellings(self):
        txt = (
            '%0 = "stablehlo.all_reduce"(%a)\n'
            '%1 = "stablehlo.all_reduce"(%b)\n'
            '%2 = "stablehlo.all_gather"(%c) {all_gather_dim = 0}\n'
            '%3 = "stablehlo.reduce_scatter"(%d)\n'
            '%4 = "stablehlo.collective_permute"(%e)\n'
        )
        assert hlo_census(txt) == {
            "all_reduce": 2,
            "all_gather": 1,
            "reduce_scatter": 1,
            "collective_permute": 1,
        }

    def test_classic_hlo_spellings(self):
        txt = (
            "ROOT %r = f32[4] all-reduce(%a), replica_groups={}\n"
            "%g = f32[32] all-gather(%b)\n"
        )
        assert hlo_census(txt) == {"all_reduce": 1, "all_gather": 1}

    def test_disagreement_raises(self, mesh8):
        tr = trace_collectives(
            _smap(lambda x: lax.psum(x, "mn"), mesh8), jnp.zeros((8, 4))
        )
        with pytest.raises(AssertionError, match="census disagreement"):
            assert_census_agreement(
                tr, '"stablehlo.all_reduce" "stablehlo.all_reduce"'
            )

"""Data-layer tests.

Parity: ``datasets_tests/test_scatter_dataset.py`` (shards partition the
set, shuffle determinism), ``iterators_tests/test_multi_node_iterator.py``,
``test_synchronized_iterator.py``.
"""

import numpy as np
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.datasets import scatter_dataset, create_empty_dataset
from chainermn_tpu.datasets.scatter_dataset import scatter_dataset_all
from chainermn_tpu.iterators import (
    SerialIterator,
    create_multi_node_iterator,
    create_synchronized_iterator,
)


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("naive", devices=devices8)


class TestScatterDataset:
    def test_process_shard_is_whole_set_single_controller(self, comm):
        ds = list(range(100))
        shard = scatter_dataset(ds, comm)
        assert len(shard) == 100

    def test_per_rank_shards_partition(self, comm):
        ds = list(range(64))
        shards = scatter_dataset_all(ds, comm)
        seen = sorted(x for s in shards for x in s[:])
        assert seen == sorted(ds)
        assert all(len(s) == 8 for s in shards)

    def test_equalized_length_with_remainder(self, comm):
        ds = list(range(61))  # not divisible by 8
        shards = scatter_dataset_all(ds, comm)
        lengths = {len(s) for s in shards}
        assert len(lengths) == 1  # every rank steps the same count
        assert sum(len(s) for s in shards) >= 61

    def test_shuffle_determinism(self, comm):
        ds = list(range(64))
        a = scatter_dataset(ds, comm, shuffle=True, seed=7, rank=3,
                            n_shards=8)
        b = scatter_dataset(ds, comm, shuffle=True, seed=7, rank=3,
                            n_shards=8)
        assert a[:] == b[:]
        c = scatter_dataset(ds, comm, shuffle=True, seed=8, rank=3,
                            n_shards=8)
        assert a[:] != c[:]

    def test_getitem_bounds(self, comm):
        ds = list(range(16))
        s = scatter_dataset(ds, comm, rank=0, n_shards=8)
        assert len(s) == 2
        with pytest.raises(IndexError):
            s[2]
        assert s[-1] == s[1]


class TestEmptyDataset:
    def test_length_preserved_and_none(self):
        ds = create_empty_dataset(list(range(37)))
        assert len(ds) == 37
        assert ds[0] is None and ds[36] is None
        with pytest.raises(IndexError):
            ds[37]


class TestSerialIterator:
    def test_epoch_accounting(self):
        ds = [(np.zeros(2), np.int32(0))] * 10
        it = SerialIterator(ds, 4, shuffle=False)
        batches = [next(it) for _ in range(5)]
        assert it.epoch >= 2
        x, y = batches[0]
        assert x.shape == (4, 2)

    def test_no_repeat_stops(self):
        ds = [(np.zeros(2), np.int32(0))] * 8
        it = SerialIterator(ds, 4, repeat=False, shuffle=False)
        n = 0
        try:
            while True:
                next(it)
                n += 1
                if n > 10:
                    break
        except StopIteration:
            pass
        assert n <= 10


class TestSynchronizedIterator:
    def test_same_order_across_ranks(self, comm):
        """Each emulated process makes its *first* synchronized-iterator
        call (reset the per-call counter to mimic a fresh process); all
        must draw the same shuffle order."""
        ds = [(np.full(1, i), np.int32(i % 3)) for i in range(32)]
        its = []
        for r in range(3):
            comm._sync_iterator_calls = 0  # fresh "process"
            its.append(
                create_synchronized_iterator(
                    SerialIterator(ds, 4, shuffle=True, seed=r), comm
                )
            )
        b0 = [next(its[0])[0].ravel().tolist() for _ in range(4)]
        for it in its[1:]:
            b = [next(it)[0].ravel().tolist() for _ in range(4)]
            assert b == b0

    def test_distinct_iterators_draw_independent_orders(self, comm):
        """Two synchronized iterators on the same communicator (train/val)
        must NOT be correlated — per-call counter mixes the seed."""
        ds = [(np.full(1, i), np.int32(0)) for i in range(32)]
        it1 = create_synchronized_iterator(
            SerialIterator(ds, 4, shuffle=True, seed=0), comm
        )
        it2 = create_synchronized_iterator(
            SerialIterator(ds, 4, shuffle=True, seed=0), comm
        )
        b1 = [next(it1)[0].ravel().tolist() for _ in range(4)]
        b2 = [next(it2)[0].ravel().tolist() for _ in range(4)]
        assert b1 != b2


class TestMultiNodeIterator:
    def test_all_ranks_see_master_stream(self, comm):
        ds = [(np.full(1, i), np.int32(0)) for i in range(16)]
        base = SerialIterator(ds, 4, shuffle=False)
        it = create_multi_node_iterator(base, comm)
        x, _ = next(it)
        assert x.shape == (4, 1)
        # attribute delegation
        assert it.batch_size == 4

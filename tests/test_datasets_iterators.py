"""Data-layer tests.

Parity: ``datasets_tests/test_scatter_dataset.py`` (shards partition the
set, shuffle determinism), ``iterators_tests/test_multi_node_iterator.py``,
``test_synchronized_iterator.py``.
"""

import numpy as np
import pytest

import chainermn_tpu as cmn
from chainermn_tpu.datasets import scatter_dataset, create_empty_dataset
from chainermn_tpu.datasets.scatter_dataset import scatter_dataset_all
from chainermn_tpu.iterators import (
    SerialIterator,
    create_multi_node_iterator,
    create_synchronized_iterator,
)


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("naive", devices=devices8)


class TestScatterDataset:
    def test_process_shard_is_whole_set_single_controller(self, comm):
        ds = list(range(100))
        shard = scatter_dataset(ds, comm)
        assert len(shard) == 100

    def test_per_rank_shards_partition(self, comm):
        ds = list(range(64))
        shards = scatter_dataset_all(ds, comm)
        seen = sorted(x for s in shards for x in s[:])
        assert seen == sorted(ds)
        assert all(len(s) == 8 for s in shards)

    def test_equalized_length_with_remainder(self, comm):
        ds = list(range(61))  # not divisible by 8
        shards = scatter_dataset_all(ds, comm)
        lengths = {len(s) for s in shards}
        assert len(lengths) == 1  # every rank steps the same count
        assert sum(len(s) for s in shards) >= 61

    def test_shuffle_determinism(self, comm):
        ds = list(range(64))
        a = scatter_dataset(ds, comm, shuffle=True, seed=7, rank=3,
                            n_shards=8)
        b = scatter_dataset(ds, comm, shuffle=True, seed=7, rank=3,
                            n_shards=8)
        assert a[:] == b[:]
        c = scatter_dataset(ds, comm, shuffle=True, seed=8, rank=3,
                            n_shards=8)
        assert a[:] != c[:]

    def test_getitem_bounds(self, comm):
        ds = list(range(16))
        s = scatter_dataset(ds, comm, rank=0, n_shards=8)
        assert len(s) == 2
        with pytest.raises(IndexError):
            s[2]
        assert s[-1] == s[1]


class TestEmptyDataset:
    def test_length_preserved_and_none(self):
        ds = create_empty_dataset(list(range(37)))
        assert len(ds) == 37
        assert ds[0] is None and ds[36] is None
        with pytest.raises(IndexError):
            ds[37]


class TestSerialIterator:
    def test_epoch_accounting(self):
        ds = [(np.zeros(2), np.int32(0))] * 10
        it = SerialIterator(ds, 4, shuffle=False)
        batches = [next(it) for _ in range(5)]
        assert it.epoch >= 2
        x, y = batches[0]
        assert x.shape == (4, 2)

    def test_no_repeat_stops(self):
        ds = [(np.zeros(2), np.int32(0))] * 8
        it = SerialIterator(ds, 4, repeat=False, shuffle=False)
        n = 0
        try:
            while True:
                next(it)
                n += 1
                if n > 10:
                    break
        except StopIteration:
            pass
        assert n <= 10

    def test_shuffled_resume_replays_across_epoch_boundary(self):
        """serialize/restore must capture the RNG: a resumed shuffled
        iterator crossing an epoch boundary reshuffles with the same
        permutation the uninterrupted run drew (the rollover inside
        __next__ calls _new_order() from the restored RNG state)."""
        ds = [(np.full(1, i), np.int32(0)) for i in range(16)]
        a = SerialIterator(ds, 4, shuffle=True, seed=5)
        next(a)
        state = a.serialize()
        # uninterrupted: run past the epoch boundary
        want = [next(a)[0].ravel().tolist() for _ in range(8)]

        b = SerialIterator(ds, 4, shuffle=True, seed=999)  # different rng
        for _ in range(6):
            next(b)  # advance rng/order arbitrarily far off-script
        b.restore(state)
        got = [next(b)[0].ravel().tolist() for _ in range(8)]
        assert got == want


class TestSynchronizedIterator:
    def test_same_order_across_ranks(self, comm):
        """Each emulated process makes its *first* synchronized-iterator
        call (reset the per-call counter to mimic a fresh process); all
        must draw the same shuffle order."""
        ds = [(np.full(1, i), np.int32(i % 3)) for i in range(32)]
        its = []
        for r in range(3):
            comm._sync_iterator_calls = 0  # fresh "process"
            its.append(
                create_synchronized_iterator(
                    SerialIterator(ds, 4, shuffle=True, seed=r), comm
                )
            )
        b0 = [next(its[0])[0].ravel().tolist() for _ in range(4)]
        for it in its[1:]:
            b = [next(it)[0].ravel().tolist() for _ in range(4)]
            assert b == b0

    def test_distinct_iterators_draw_independent_orders(self, comm):
        """Two synchronized iterators on the same communicator (train/val)
        must NOT be correlated — per-call counter mixes the seed."""
        ds = [(np.full(1, i), np.int32(0)) for i in range(32)]
        it1 = create_synchronized_iterator(
            SerialIterator(ds, 4, shuffle=True, seed=0), comm
        )
        it2 = create_synchronized_iterator(
            SerialIterator(ds, 4, shuffle=True, seed=0), comm
        )
        b1 = [next(it1)[0].ravel().tolist() for _ in range(4)]
        b2 = [next(it2)[0].ravel().tolist() for _ in range(4)]
        assert b1 != b2


class TestMultiNodeIterator:
    def test_all_ranks_see_master_stream(self, comm):
        ds = [(np.full(1, i), np.int32(0)) for i in range(16)]
        base = SerialIterator(ds, 4, shuffle=False)
        it = create_multi_node_iterator(base, comm)
        x, _ = next(it)
        assert x.shape == (4, 1)
        # attribute delegation
        assert it.batch_size == 4


class TestDevicePrefetch:
    """prefetch_to_device must (a) preserve the stream, (b) return
    PLACED arrays, and (c) stay `depth` transfers ahead of the consumer
    — the H2D/compute overlap that hides input latency."""

    def _batches(self, n=6):
        return [np.full((8, 2), float(i), np.float32) for i in range(n)]

    def test_stream_preserved_and_placed(self, devices8):
        import jax
        import optax

        from chainermn_tpu.iterators import prefetch_to_device
        from chainermn_tpu.optimizers import build_train_step

        tcomm = cmn.create_communicator("tpu", devices=devices8)
        step = build_train_step(
            tcomm, lambda p, b: (p["w"] * b).sum(),
            cmn.create_multi_node_optimizer(optax.sgd(0.1), tcomm),
        )
        it = prefetch_to_device(iter(self._batches()), step.place_batch)
        got = list(it)
        assert len(got) == 6
        for i, b in enumerate(got):
            assert isinstance(b, jax.Array)
            assert b.sharding == step.batch_sharding
            np.testing.assert_array_equal(np.asarray(b), np.full((8, 2), i))

    def test_prefetch_depth_ahead(self):
        from chainermn_tpu.iterators import prefetch_to_device

        placed = []

        def place(x):
            placed.append(int(x[0, 0]))
            return x

        it = prefetch_to_device(iter(self._batches()), place, depth=2)
        first = next(it)
        assert int(first[0, 0]) == 0
        # while the caller computes on batch 0, batches 1 AND 2 are
        # already dispatched (one popped slot refilled + depth ahead)
        assert placed == [0, 1, 2]
        next(it)
        assert placed == [0, 1, 2, 3]

    def test_exhaustion_drains_buffer(self):
        from chainermn_tpu.iterators import prefetch_to_device

        it = prefetch_to_device(iter(self._batches(3)), lambda x: x,
                                depth=4)
        assert len(list(it)) == 3
        with pytest.raises(StopIteration):
            next(it)

    def test_bad_depth_rejected(self):
        from chainermn_tpu.iterators import prefetch_to_device

        with pytest.raises(ValueError, match="depth"):
            prefetch_to_device(iter([]), lambda x: x, depth=0)

    def test_bookkeeping_passthrough(self):
        from chainermn_tpu.iterators import prefetch_to_device

        base = SerialIterator(list(range(16)), 4, shuffle=False)
        it = prefetch_to_device(base, lambda x: x)
        assert it.batch_size == 4

    def test_serialize_rewinds_to_oldest_buffered(self):
        """Checkpoint resume must not skip the buffered-but-unconsumed
        batches the prefetcher raced ahead on: serialize() returns the
        state as of the oldest unconsumed batch, so a fresh prefetcher
        restored from it replays exactly the not-yet-consumed stream."""
        from chainermn_tpu.iterators import prefetch_to_device

        ds = list(range(16))
        base = SerialIterator(ds, 4, shuffle=False)
        it = prefetch_to_device(base, lambda x: x, depth=2)
        consumed = [next(it), next(it)]  # buffer holds batches 2,3
        assert [b[0] for b in consumed] == [0, 4]
        state = it.serialize()

        base2 = SerialIterator(ds, 4, shuffle=False)
        it2 = prefetch_to_device(base2, lambda x: x, depth=2)
        it2.restore(state)
        resumed = [next(it2), next(it2)]
        # batches 8 and 12 — not 16-wrapped past the raced-ahead point
        assert [b[0] for b in resumed] == [8, 12]

    def test_no_serialize_stays_undetectable(self):
        """A wrapped iterator without serialize() must leave the
        prefetcher without one too — Trainer.state_dict feature-detects
        with hasattr and treats absence as a graceful no-op; growing a
        serialize() that raises would turn that into a checkpoint-time
        crash."""
        from chainermn_tpu.iterators import prefetch_to_device

        it = prefetch_to_device(iter(self._batches(2)), lambda x: x)
        assert not hasattr(it, "serialize")
        assert not hasattr(it, "restore")

    def test_serialize_without_buffer_passthrough(self):
        """Exhausted prefetcher (empty buffer): serialize() falls back
        to the wrapped iterator's current state.  Uses a FINITE
        serializable iterator — SerialIterator repeats forever, so
        list() on it would never terminate."""
        from chainermn_tpu.iterators import prefetch_to_device

        class FiniteIt:
            def __init__(self):
                self.pos = 0

            def __next__(self):
                if self.pos >= 3:
                    raise StopIteration
                self.pos += 1
                return self.pos

            def __iter__(self):
                return self

            def serialize(self):
                return {"pos": self.pos}

        base = FiniteIt()
        it = prefetch_to_device(base, lambda x: x, depth=4)
        assert list(it) == [1, 2, 3]  # exhaust: buffer empty
        assert it.serialize() == {"pos": 3}

    def test_snapshot_states_false_hides_serialize(self):
        """snapshot_states=False (for wrapped iterators whose
        serialize() is not O(1)): per-batch snapshotting stops AND the
        prefetcher exposes no serialize() at all — a passthrough to the
        wrapped iterator would checkpoint the raced-ahead position and
        silently drop the buffered batches at resume (advisor r4)."""
        from chainermn_tpu.iterators import prefetch_to_device

        calls = []

        class CountingIt:
            def __init__(self):
                self.pos = 0

            def __next__(self):
                self.pos += 1
                return self.pos

            def __iter__(self):
                return self

            def serialize(self):
                calls.append(self.pos)
                return {"pos": self.pos}

        it = prefetch_to_device(CountingIt(), lambda x: x, depth=2,
                                snapshot_states=False)
        assert [next(it), next(it)] == [1, 2]
        assert calls == []  # serialize never invoked per batch
        assert not hasattr(it, "serialize")  # and not exposed either

"""Communicator test matrix.

Parity: ``tests/chainermn_tests/communicator_tests/test_communicator.py`` —
one parametrized suite run against every communicator variant, checking
bcast/allreduce numerics, send/recv round-trips, obj variants, split.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import chainermn_tpu as cmn
from chainermn_tpu.communicators import create_communicator

ALL_NAMES = [
    "tpu", "pure_nccl", "flat", "hierarchical", "two_dimensional",
    "single_node", "naive", "non_cuda_aware",
]
# `dummy` intentionally does no exchange; tested separately.


@pytest.fixture(params=ALL_NAMES, scope="module")
def comm(request, devices8):
    return create_communicator(request.param, devices=devices8)


def _stack(comm, shape=(3,), dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(
        rng.randn(comm.size, *shape).astype(dtype)
    )


class TestRankModel:
    def test_size_and_ranks(self, comm):
        assert comm.size == 8
        assert comm.inter_size * comm.intra_size == comm.size or (
            comm.inter_size == 1
        )
        assert 0 <= comm.rank < comm.size
        assert comm.local_ranks == tuple(range(8))

    def test_topology_consistency(self, comm):
        t = comm.topology
        assert len(t.devices) == 8
        assert t.inter_size >= 1
        for r in range(8):
            assert 0 <= t.intra_ranks[r] < t.intra_sizes[r]


class TestCollectives:
    def test_allreduce_sum(self, comm):
        x = _stack(comm)
        out = np.asarray(comm.allreduce(x, op="sum"))
        expect = np.asarray(x).sum(axis=0)
        for r in range(comm.size):
            np.testing.assert_allclose(out[r], expect, rtol=1e-5)

    def test_allreduce_mean_max_min(self, comm):
        x = _stack(comm, seed=1)
        h = np.asarray(x)
        for op, ref in [("mean", h.mean(0)), ("max", h.max(0)), ("min", h.min(0))]:
            out = np.asarray(comm.allreduce(x, op=op))
            for r in range(comm.size):
                np.testing.assert_allclose(out[r], ref, rtol=1e-5)

    @pytest.mark.parametrize("root", [0, 3, 7])
    def test_bcast(self, comm, root):
        x = _stack(comm, seed=2)
        out = np.asarray(comm.bcast(x, root=root))
        for r in range(comm.size):
            np.testing.assert_allclose(out[r], np.asarray(x)[root], rtol=1e-6)

    def test_allgather(self, comm):
        x = _stack(comm, seed=3)
        out = np.asarray(comm.allgather(x))
        np.testing.assert_allclose(out, np.asarray(x), rtol=1e-6)

    def test_gather(self, comm):
        x = _stack(comm, seed=4)
        out = np.asarray(comm.gather(x, root=2))
        np.testing.assert_allclose(out, np.asarray(x), rtol=1e-6)

    def test_gather_scatter_placement(self, comm):
        """Placement contract across ALL tiers, incl. the naive oracle
        (round-4 weak #7: naive.gather used to blur into allgather, so
        it could not catch a root-placement bug in the XLA tier):
        gather materializes the full stack on ``devices[root]`` ONLY;
        scatter distributes one row per device over the comm's set."""
        x = _stack(comm, seed=11)
        g = comm.gather(x, root=2)
        assert g.devices() == {comm.devices[2]}

        s = comm.scatter(x)
        assert s.devices() == set(comm.devices)
        for sh in s.addressable_shards:
            assert sh.data.shape[0] == 1  # exactly one row per device

    def test_alltoall(self, comm):
        x = jnp.arange(comm.size * comm.size * 2, dtype=jnp.float32).reshape(
            comm.size, comm.size, 2
        )
        out = np.asarray(comm.alltoall(x))
        np.testing.assert_allclose(out, np.swapaxes(np.asarray(x), 0, 1))

    def test_send_recv_roundtrip(self, comm):
        x = _stack(comm, seed=5)
        moved = comm.send(x, dest=6, source=1)
        h = np.asarray(moved)
        np.testing.assert_allclose(h[6], np.asarray(x)[1], rtol=1e-6)
        back = np.asarray(comm.recv(moved, source=6, dest=1))
        np.testing.assert_allclose(back[1], np.asarray(x)[1], rtol=1e-6)

    def test_reduce_scatter(self, comm):
        x = _stack(comm, shape=(16,), seed=6)
        out = np.asarray(comm.reduce_scatter(x, op="sum"))
        full = np.asarray(x).sum(0).reshape(comm.size, -1)
        np.testing.assert_allclose(out, full, rtol=1e-5)

    def test_multidim_payload(self, comm):
        x = _stack(comm, shape=(4, 5), seed=7)
        out = np.asarray(comm.allreduce(x))
        np.testing.assert_allclose(out[0], np.asarray(x).sum(0), rtol=1e-5)


class TestSplit:
    def test_split_halves(self, comm):
        subs = comm.split([0, 0, 0, 0, 1, 1, 1, 1])
        assert set(subs) == {0, 1}
        for color, sub in subs.items():
            assert sub.size == 4
            x = jnp.arange(4.0).reshape(4, 1)
            out = np.asarray(sub.allreduce(x))
            np.testing.assert_allclose(out, 6.0)

    def test_split_undefined_color(self, comm):
        subs = comm.split([0, 0, None, None, None, None, None, None])
        assert set(subs) == {0}
        assert subs[0].size == 2

    def test_split_key_reorders(self, comm):
        subs = comm.split([0] * 8, keys=[7, 6, 5, 4, 3, 2, 1, 0])
        sub = subs[0]
        assert sub.size == 8


class TestObjTransport:
    def test_bcast_obj(self, comm):
        obj = {"step": 3, "names": ["a", "b"]}
        assert comm.bcast_obj(obj) == obj

    def test_gather_allgather_obj(self, comm):
        objs = comm.allgather_obj(("x", 1))
        assert objs == [("x", 1)] * comm.size
        objs = comm.gather_obj(5)
        assert objs == [5] * comm.size

    def test_allreduce_obj(self, comm):
        assert comm.allreduce_obj(2.5) == 2.5 * comm.size

    def test_send_recv_obj(self, comm):
        comm.send_obj({"payload": 42}, dest=0, tag=9)
        assert comm.recv_obj(source=1, tag=9) == {"payload": 42}

    def test_send_recv_obj_nonzero_dest(self, comm):
        # Regression: LocalObjStore.recv used to drain rank 0's mailbox
        # regardless of destination, making dest != 0 unreceivable.
        comm.send_obj("for-three", dest=3, tag=4)
        comm.send_obj("for-zero", dest=0, tag=4)
        assert comm.recv_obj(source=0, tag=4, dest=3) == "for-three"
        assert comm.recv_obj(source=0, tag=4, dest=0) == "for-zero"

    def test_recv_obj_wrong_dest_raises(self, comm):
        comm.send_obj("x", dest=5, tag=11)
        with pytest.raises(RuntimeError):
            comm.recv_obj(source=0, tag=11, dest=2)
        assert comm.recv_obj(source=0, tag=11, dest=5) == "x"

    def test_recv_obj_dest_out_of_range(self, comm):
        with pytest.raises(ValueError):
            comm.recv_obj(source=0, tag=0, dest=comm.size)


class TestModelLevel:
    def test_bcast_data_replicates(self, comm):
        tree = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        out = comm.bcast_data(tree)
        assert out["w"].shape == (4, 4)
        np.testing.assert_allclose(np.asarray(out["w"]), 1.0)

    def test_allreduce_grad_means(self, comm):
        grads = {"w": _stack(comm, shape=(2, 2), seed=8)}
        out = comm.allreduce_grad(grads)
        expect = np.asarray(grads["w"]).mean(0)
        for r in range(comm.size):
            np.testing.assert_allclose(
                np.asarray(out["w"])[r], expect, rtol=1e-5
            )


class TestReducedPrecision:
    @pytest.mark.parametrize("name", ["tpu", "hierarchical", "naive"])
    def test_allreduce_grad_bf16(self, name, devices8):
        comm = create_communicator(
            name, devices=devices8, allreduce_grad_dtype=jnp.bfloat16
        )
        g = jnp.ones((8, 16), jnp.float32)
        out = comm.allreduce_grad({"g": g})["g"]
        assert out.dtype == jnp.float32 or out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32), 1.0, rtol=1e-2)


class TestDummy:
    def test_dummy_passthrough(self, devices8):
        comm = create_communicator("dummy", devices=devices8)
        x = jnp.arange(8.0).reshape(8, 1)
        np.testing.assert_allclose(np.asarray(comm.allreduce(x)), np.asarray(x))

    def test_dummy_compiled_tier_skips_exchange(self, devices8):
        """build_train_step(dummy) must be the real step's exact twin
        minus the gradient exchange (the reference's subtraction
        methodology at the compiled tier): (a) the first step's loss —
        computed before any update — matches the synced step bit-for-
        bit; (b) after that step, ranks hold *diverged* params under
        dummy (each applied only its local grads) while the synced step
        keeps them replicated-equal."""
        import optax

        import chainermn_tpu as cmn
        from chainermn_tpu.models import MLP

        def build(name):
            comm = create_communicator(name, devices=devices8)
            model = MLP(n_units=16, n_out=4, dtype=jnp.float32)
            params = model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8, 8))
            )
            opt = cmn.create_multi_node_optimizer(optax.sgd(0.5), comm)

            def loss_fn(p, b):
                x, y = b
                logits = model.apply(p, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean()

            step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
            params, opt_state = step.place(params, opt.init(params))
            rng = np.random.RandomState(0)
            # rank-varying batch so local grads genuinely differ
            x = jnp.asarray(rng.randn(16, 8, 8), jnp.float32)
            y = jnp.asarray(rng.randint(0, 4, (16,)), jnp.int32)
            return step, params, opt_state, (x, y)

        step_s, p_s, o_s, batch = build("tpu")
        step_d, p_d, o_d, _ = build("dummy")
        p_s2, o_s2, m_s = step_s(p_s, o_s, batch)
        p_d2, o_d2, m_d = step_d(p_d, o_d, batch)
        # (a) pre-update loss identical: same forward, same pmean
        assert float(m_s["loss"]) == pytest.approx(
            float(m_d["loss"]), rel=1e-6
        )

        def shards(tree):
            leaf = jax.tree_util.tree_leaves(tree)[0]
            return [np.asarray(s.data) for s in leaf.addressable_shards]

        # (b) sync keeps params replicated; dummy lets ranks diverge
        s_shards = shards(p_s2)
        d_shards = shards(p_d2)
        for sh in s_shards[1:]:
            np.testing.assert_array_equal(sh, s_shards[0])
        assert any(
            not np.array_equal(sh, d_shards[0]) for sh in d_shards[1:]
        )


class TestNonCudaAwareContract:
    def test_every_collective_stages_through_host(self, devices8,
                                                  monkeypatch):
        """The variant's contract: NO XLA collective program in the data
        path — every op is device_get -> NumPy -> device_put.  Building a
        shard_map program here would mean an op silently inherited the
        XLA path (the round-1 bug: only allreduce was host-staged)."""
        from chainermn_tpu.communicators.variants import (
            NonCudaAwareCommunicator,
        )

        comm = create_communicator("non_cuda_aware", devices=devices8)

        def boom(self, *a, **kw):
            raise AssertionError(
                "host-staged variant built an XLA collective program"
            )

        monkeypatch.setattr(NonCudaAwareCommunicator, "_shard", boom)
        x = _stack(comm, shape=(4,))
        h = np.asarray(x)
        np.testing.assert_allclose(
            np.asarray(comm.allreduce(x))[0], h.sum(0), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(comm.bcast(x, root=5))[2], h[5], rtol=1e-6
        )
        np.testing.assert_allclose(np.asarray(comm.allgather(x)), h)
        np.testing.assert_allclose(np.asarray(comm.gather(x, root=1)), h)
        np.testing.assert_allclose(np.asarray(comm.scatter(x)), h)
        a2a = _stack(comm, shape=(comm.size, 2), seed=4)
        np.testing.assert_allclose(
            np.asarray(comm.alltoall(a2a)),
            np.swapaxes(np.asarray(a2a), 0, 1),
        )
        sent = np.asarray(comm.send(x, dest=3, source=6))
        np.testing.assert_allclose(sent[3], h[6])
        rs = _stack(comm, shape=(comm.size * 2,), seed=5)
        out = np.asarray(comm.reduce_scatter(rs))
        np.testing.assert_allclose(
            out.reshape(-1), np.asarray(rs).sum(0), rtol=1e-5
        )
        grads = comm.allreduce_grad({"g": x})
        np.testing.assert_allclose(
            np.asarray(grads["g"])[0], h.mean(0), rtol=1e-5
        )


class TestSingleNodeAssert:
    def test_single_node_ok_on_one_host(self, devices8):
        comm = create_communicator("single_node", devices=devices8)
        assert comm.inter_size == 1


class TestFactory:
    def test_unknown_name_raises(self):
        with pytest.raises(ValueError, match="unknown communicator"):
            create_communicator("warp_drive")

    def test_default_spans_all_devices(self, devices8):
        comm = create_communicator("naive", devices=devices8)
        assert comm.size == len(devices8)

"""Runtime telemetry tests (ISSUE 10).

Load-bearing pins, in order:

* the DISABLED-path overhead contract: with no telemetry active, the
  full per-step span-site cost is <= 1 % of a compiled MLP step on the
  8-device CPU mesh (the instrumentation is permanently in the hot
  path — the contract is what makes that acceptable);
* a 3-step CPU-mesh trainer run exports a Chrome trace whose JSON
  shape is valid (the tier-1 smoke of the satellite checklist);
* ``observability.attribute`` joins the ResNet-50 step's 5 all-reduce
  records (4 bucket psums + the loss pmean) to measured collective
  spans BYTE-EXACTLY, with achieved-bandwidth figures (the acceptance
  criterion);
* ``ResilienceEvent`` now carries monotonic + wall time and the
  process index, ``emit`` shares ONE event object across sinks, and
  ``Timeline.merge_resilience`` is idempotent across logs (the
  satellite fix that makes the merged stream deterministic);
* ``time_steps`` returns its raw paired-difference samples and
  ``Histogram.protocol_fields`` defers to the one shared min-of-N
  helper.
"""

import itertools
import json
import os
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

import chainermn_tpu as cmn
from chainermn_tpu import observability as obs
from chainermn_tpu.observability import timeline as tl_mod
from chainermn_tpu.resilience.log import (
    ResilienceLog,
    attach,
    detach,
    emit,
)
from chainermn_tpu.training.trainer import Trainer, Updater
from chainermn_tpu.utils.benchmarking import protocol_fields, time_steps


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("tpu", devices=devices8)


@pytest.fixture(autouse=True)
def _no_leaked_telemetry():
    """Every test must leave the process-global telemetry disabled."""
    yield
    assert obs.active() is None, "test leaked an installed Telemetry"
    obs.install(None)


def _mlp_trainer(comm, n_units=50, stop=(3, "iteration")):
    from chainermn_tpu.models import MLP

    model = MLP(n_units=n_units)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))

    def loss_fn(p, b):
        x, y = b
        return optax.softmax_cross_entropy_with_integer_labels(
            model.apply(p, x), y
        ).mean()

    opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)
    step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
    p, o = step.place(params, opt.init(params))
    x = np.random.RandomState(0).rand(16, 28, 28).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, (16,)).astype(np.int32)
    it = itertools.cycle([(x, y)])
    return Trainer(Updater(it, step, p, o), stop_trigger=stop)


# ----------------------------------------------------------------------
# metrics registry
# ----------------------------------------------------------------------
class TestMetrics:
    def test_counter_gauge_histogram(self):
        reg = obs.MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(2)
        assert reg.counter("c").value == 3
        reg.gauge("g").set(1.5)
        assert reg.gauge("g").value == 1.5
        h = reg.histogram("h")
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        assert h.count == 4
        assert h.mean == 2.5
        assert h.percentile(50) == 2.5
        assert h.max == 4.0
        snap = reg.snapshot()
        assert snap["counters"]["c"] == 3
        assert snap["histograms"]["h"]["count"] == 4

    def test_get_or_create_is_stable(self):
        reg = obs.MetricsRegistry()
        assert reg.histogram("x") is reg.histogram("x")
        assert not reg.has_histogram("y")

    def test_histogram_protocol_fields_share_the_bench_helper(self):
        """One source for spread: Histogram.protocol_fields ==
        utils.benchmarking.protocol_fields on the same samples."""
        h = obs.Histogram("t")
        samples = [0.01, 0.012, 0.011, -0.001]
        h.extend(samples)
        assert h.protocol_fields() == protocol_fields(samples)
        assert h.spread_max_over_min == pytest.approx(0.012 / 0.01)

    def test_histogram_spread_absent_below_two_positive(self):
        h = obs.Histogram("t")
        h.observe(0.01)
        assert h.protocol_fields() == {"n_measurements": 1}
        assert h.spread_max_over_min is None


# ----------------------------------------------------------------------
# timeline + activation
# ----------------------------------------------------------------------
class TestTimeline:
    def test_disabled_span_is_null(self):
        assert obs.active() is None
        cm = obs.span("anything", x=1)
        assert cm is obs.NULL_SPAN
        with cm as sp:
            sp.set(y=2)  # no-op, must not raise

    def test_nesting_records_parent_ids(self):
        with obs.observe() as tel:
            with obs.span("outer"):
                with obs.span("inner"):
                    pass
        spans = {s["name"]: s for s in tel.timeline.spans()}
        assert spans["inner"]["parent"] == spans["outer"]["sid"]
        assert spans["outer"]["parent"] == 0

    def test_observe_nesting_restores_previous(self):
        with obs.observe() as a:
            assert obs.active() is a
            with obs.observe() as b:
                assert obs.active() is b
            assert obs.active() is a
        assert obs.active() is None

    def test_span_durations_feed_histograms(self):
        with obs.observe() as tel:
            with obs.span("phase"):
                pass
            with obs.span("phase"):
                pass
        h = tel.registry.histogram("phase")
        assert h.count == 2
        assert all(v >= 0 for v in h.values)

    def test_set_attaches_args_mid_span(self):
        with obs.observe() as tel:
            with obs.span("s") as sp:
                sp.set(bytes=42)
        assert tel.timeline.spans("s")[0]["args"]["bytes"] == 42

    def test_events_sorted_by_time(self):
        with obs.observe() as tel:
            tel.timeline.instant("late", t=tel.timeline.t0 + 100.0)
            tel.timeline.instant("early", t=tel.timeline.t0 + 1.0)
        names = [e["name"] for e in tel.timeline.events()]
        assert names == ["early", "late"]

    def test_env_activation(self, monkeypatch):
        monkeypatch.setenv(tl_mod.ENV_TELEMETRY, "1")
        tl_mod._from_env()
        try:
            assert obs.active() is not None
        finally:
            obs.install(None)
        monkeypatch.setenv(tl_mod.ENV_TELEMETRY, "0")
        tl_mod._from_env()  # "0" must NOT activate
        assert obs.active() is None

    def test_chrome_trace_shape(self, tmp_path):
        with obs.observe() as tel:
            with obs.span("s", bucket=1):
                pass
            obs.instant("mark")
        path = tel.timeline.to_chrome_trace(
            str(tmp_path / "trace.json")
        )
        doc = json.loads(open(path).read())
        assert isinstance(doc["traceEvents"], list)
        phs = [e["ph"] for e in doc["traceEvents"]]
        assert "M" in phs and "X" in phs and "i" in phs
        for e in doc["traceEvents"]:
            assert "name" in e and "pid" in e and "tid" in e
            if e["ph"] == "X":
                assert e["dur"] >= 0 and isinstance(e["ts"], float)

    def test_jsonl_export(self, tmp_path):
        with obs.observe() as tel:
            with obs.span("s"):
                pass
        path = tel.timeline.to_jsonl(str(tmp_path / "t.jsonl"))
        rows = [json.loads(l) for l in open(path)]
        assert rows and rows[0]["type"] == "span"
        assert rows[0]["name"] == "s" and rows[0]["dur"] >= 0


class TestResilienceMerge:
    def test_event_carries_both_clocks_and_process(self):
        log = ResilienceLog()
        before = time.monotonic()
        ev = log.record("fault_injected", "site", fault="timeout")
        assert before <= ev.monotonic <= time.monotonic()
        assert ev.time > 0  # wall clock
        assert ev.process == 0
        # the query surface is unchanged
        assert log.counts == {"fault_injected": 1}

    def test_emit_shares_one_event_object_across_sinks(self):
        a, b = ResilienceLog(), ResilienceLog()
        attach(a)
        attach(b)
        try:
            emit("retry", "s", attempt=1)
        finally:
            detach(a)
            detach(b)
        assert len(a) == len(b) == 1
        assert a.events()[0] is b.events()[0]

    def test_merge_positions_and_idempotence(self):
        a, b = ResilienceLog(), ResilienceLog()
        attach(a)
        attach(b)
        try:
            emit("fault_injected", "obj_store.recv", fault="timeout")
            emit("retry", "obj_store.recv", attempt=1)
        finally:
            detach(a)
            detach(b)
        with obs.observe() as tel:
            assert tel.timeline.merge_resilience(a) == 2
            # same event OBJECTS via the other sink: deduped
            assert tel.timeline.merge_resilience(b) == 0
            assert tel.timeline.merge_resilience(a) == 0
        evs = tel.timeline.events()
        assert [e["name"] for e in evs] == [
            "resilience.fault_injected", "resilience.retry",
        ]
        assert evs[0]["t"] <= evs[1]["t"]
        assert evs[0]["args"]["site"] == "obj_store.recv"

    def test_merge_survives_garbage_collected_prior_log(self):
        """Review regression: the merge dedupe must HOLD the merged
        event objects — a bare id() set lets a freed log's event
        addresses recycle into later logs' events, which then silently
        vanish from the export."""
        import gc

        with obs.observe() as tel:
            log_a = ResilienceLog()
            for i in range(5):
                log_a.record("fault_injected", f"a{i}")
            assert tel.timeline.merge_resilience(log_a) == 5
            del log_a
            gc.collect()
            log_b = ResilienceLog()
            for i in range(5):
                log_b.record("retry", f"b{i}")
            assert tel.timeline.merge_resilience(log_b) == 5

    def test_own_telemetry_uninstalled_when_run_raises(self, comm):
        """Review regression: extension finalize runs on error exits
        too — a MetricsReport that installed its own process-global
        telemetry must not leak it past a failed run."""
        from chainermn_tpu.resilience import FaultSpec, inject_faults
        from chainermn_tpu.resilience.errors import (
            RestartBudgetExceededError,
            TransientCommError,
        )

        trainer = _mlp_trainer(comm)
        trainer.extend(obs.MetricsReport(
            comm, trigger=(1, "iteration"), filename=None
        ))
        assert obs.active() is None
        with inject_faults([
            FaultSpec("trainer.update", "timeout", at=[1, 2, 3, 4, 5]),
        ]):
            with pytest.raises(
                (RestartBudgetExceededError, TransientCommError)
            ):
                trainer.run(max_restarts=1)
        assert obs.active() is None

    def test_trainer_run_auto_merges_into_active_timeline(self, comm):
        from chainermn_tpu.resilience import FaultSpec, inject_faults

        trainer = _mlp_trainer(comm)
        with obs.observe() as tel:
            with inject_faults([
                FaultSpec("trainer.update", "timeout", at=[2]),
            ]):
                trainer.run(max_restarts=1)
        names = [e["name"] for e in tel.timeline.events()]
        assert "resilience.fault_injected" in names
        assert "resilience.restart" in names
        # and the instants sit inside the span stream, time-ordered
        ts = [e["t"] for e in tel.timeline.events()]
        assert ts == sorted(ts)


# ----------------------------------------------------------------------
# instrumented trainer (the tier-1 chrome-trace smoke)
# ----------------------------------------------------------------------
class TestTrainerInstrumentation:
    def test_three_step_run_exports_valid_chrome_trace(
        self, comm, tmp_path
    ):
        trainer = _mlp_trainer(comm)
        with obs.observe() as tel:
            trainer.run()
        assert trainer.iteration == 3
        for name in ("step", "update", "data.wait", "compute.dispatch"):
            assert len(tel.timeline.spans(name)) == 3, name
            assert tel.registry.histogram(name).count == 3
        # step nests update nests data.wait/compute.dispatch
        spans = tel.timeline.spans()
        by_id = {s["sid"]: s for s in spans}
        for s in spans:
            if s["name"] == "data.wait":
                assert by_id[s["parent"]]["name"] == "update"
            if s["name"] == "update":
                assert by_id[s["parent"]]["name"] == "step"
        path = tel.timeline.to_chrome_trace(
            str(tmp_path / "train.json")
        )
        doc = json.loads(open(path).read())
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) >= 12  # 4 span kinds x 3 steps
        assert all(e["dur"] >= 0 for e in xs)
        assert any(e["name"] == "step" for e in xs)

    def test_disabled_run_records_nothing_and_matches_numerics(
        self, comm
    ):
        t1 = _mlp_trainer(comm)
        t1.run()
        with obs.observe() as tel:
            t2 = _mlp_trainer(comm)
            t2.run()
        np.testing.assert_array_equal(
            np.asarray(jax.tree_util.tree_leaves(t1.updater.params)[0]),
            np.asarray(jax.tree_util.tree_leaves(t2.updater.params)[0]),
        )
        assert len(tel.timeline) > 0

    def test_disabled_overhead_at_most_one_percent_of_step(self, comm):
        """The overhead contract pinned: per-step disabled-path span
        cost (every span site the step taxonomy hits, with headroom)
        must be <= 1 % of a compiled MLP step on the 8-device mesh."""
        assert obs.active() is None
        n = 20000
        t0 = time.monotonic()
        for _ in range(n):
            with obs.span("x"):
                pass
        per_span = (time.monotonic() - t0) / n

        trainer = _mlp_trainer(comm, stop=(12, "iteration"))
        trainer.run()  # warm compile + a few iterations
        upd = trainer.updater
        t0 = time.monotonic()
        for _ in range(10):
            upd.update()
        jax.block_until_ready(upd.last_metrics["loss"])
        step_s = (time.monotonic() - t0) / 10

        spans_per_step = 8  # 4 taxonomy sites + generous headroom
        assert spans_per_step * per_span <= 0.01 * step_s, (
            f"disabled span cost {per_span * 1e6:.2f}us x "
            f"{spans_per_step} vs step {step_s * 1e3:.2f}ms"
        )


# ----------------------------------------------------------------------
# eager wire spans + attribution
# ----------------------------------------------------------------------
class TestWireSpans:
    def test_eager_bucket_psums_recorded_with_bytes(self, comm):
        from chainermn_tpu.comm_wire import make_plan

        grads = {
            "a": jnp.ones((comm.size, 2_000_000), jnp.float32),
            "b": jnp.ones((comm.size, 64), jnp.float32),
        }
        plan = make_plan([grads["a"][0], grads["b"][0]])
        assert plan.n_buckets >= 2
        with obs.observe() as tel:
            out = comm.allreduce_grad(grads)
        psums = tel.timeline.spans("collective.psum")
        assert len(psums) == plan.n_buckets
        for k, sp in enumerate(sorted(
            psums, key=lambda s: s["args"]["bucket"]
        )):
            b = plan.buckets[k]
            assert sp["args"]["bytes"] == b.size * np.dtype(
                b.dtype
            ).itemsize
        assert len(tel.timeline.spans("wire.ship")) == plan.n_buckets
        assert len(tel.timeline.spans("wire.pack")) == 1
        # telemetry must not change the numbers
        base = comm.allreduce_grad(grads)
        np.testing.assert_array_equal(
            np.asarray(out["a"]), np.asarray(base["a"])
        )

    def test_measured_issue_report_delays_nonnegative(self, comm):
        grads = {"a": jnp.ones((comm.size, 2_000_000), jnp.float32)}
        with obs.observe() as tel:
            comm.allreduce_grad(grads)
        groups = obs.measured_issue_report(tel)
        assert len(groups) == 1
        for issue in groups[0]:
            assert issue.delay_s >= 0
            assert issue.duration_s > 0
            assert issue.bucket >= 0

    def test_host_staged_tier_records_reduce_and_ship(self, devices8):
        nca = cmn.create_communicator(
            "non_cuda_aware", devices=devices8
        )
        grads = {"w": jnp.ones((nca.size, 50_000), jnp.float32)}
        with obs.observe() as tel:
            out = nca.allreduce_grad(grads)
        assert len(tel.timeline.spans("wire.reduce")) >= 1
        assert len(tel.timeline.spans("wire.ship")) >= 1
        r = tel.timeline.spans("wire.reduce")[0]
        assert r["args"]["bytes"] == 50_000 * 4
        base = nca.allreduce_grad(grads)
        np.testing.assert_array_equal(
            np.asarray(out["w"]), np.asarray(base["w"])
        )

    def test_obj_store_spans(self, comm):
        with obs.observe() as tel:
            comm.send_obj({"k": 1}, dest=1, tag=9)
            comm.recv_obj(source=0, tag=9, dest=1)
            comm.allgather_obj([1, 2])
        assert len(tel.timeline.spans("obj_store.send")) == 1
        assert len(tel.timeline.spans("obj_store.recv")) == 1
        assert len(tel.timeline.spans("obj_store.exchange")) == 1
        for s in tel.timeline.spans("obj_store.send"):
            assert s["args"]["bytes"] > 0

    def test_checkpoint_spans(self, comm, tmp_path):
        ckpt = cmn.create_multi_node_checkpointer(
            "obs", comm, path=str(tmp_path), use_orbax=False
        )
        state = {"a": np.arange(4, dtype=np.float32)}
        with obs.observe() as tel:
            ckpt.save(3, state)
            step, got = ckpt.resume()
        assert step == 3
        np.testing.assert_array_equal(got["a"], state["a"])
        assert len(tel.timeline.spans("checkpoint.save")) == 1
        assert len(tel.timeline.spans("checkpoint.resume")) == 1
        assert len(tel.timeline.spans("checkpoint.agreement")) == 1


class TestAttribution:
    def test_attribute_joins_resnet50_bucket_psums(self, comm):
        """The acceptance criterion: the ResNet-50 step's 5 all-reduce
        records (4 default-plan bucket psums + the loss pmean) join to
        measured collective spans byte-exactly, each priced with an
        achieved-bandwidth figure.  Static side: the compiled step's
        trace over eval_shape params (nothing runs).  Measured side:
        the eager bucketed wire on a 2-device sub-communicator (same
        shapes -> same deterministic plan -> same per-rank bucket
        bytes), plus one eager scalar mean for the pmean analogue."""
        from chainermn_tpu.comm_wire import plan_of_tree
        from chainermn_tpu.models import ResNet50

        model = ResNet50(num_classes=1000, train=False)
        pshapes = jax.eval_shape(
            model.init, jax.random.PRNGKey(0),
            jnp.zeros((1, 32, 32, 3)),
        )
        plan = plan_of_tree(pshapes)

        def loss_fn(p, b):
            x, y = b
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x), y
            ).mean()

        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)
        step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
        ostate = jax.eval_shape(opt.init, pshapes)
        batch = (
            jax.device_put(jnp.zeros((8, 32, 32, 3)),
                           step.batch_sharding),
            jax.device_put(jnp.zeros((8,), jnp.int32),
                           step.batch_sharding),
        )
        trace = step.collective_trace(pshapes, ostate, batch)
        assert trace.count("all_reduce") == plan.n_buckets + 1

        comm2 = cmn.create_communicator(
            "tpu", devices=jax.devices()[:2]
        )
        leaves, treedef = jax.tree_util.tree_flatten(pshapes)
        grads = jax.tree_util.tree_unflatten(treedef, [
            np.zeros((2,) + tuple(l.shape), l.dtype) for l in leaves
        ])
        with obs.observe() as tel:
            comm2.allreduce_grad(grads)
            comm2.allreduce(np.zeros((2,), np.float32), op="mean")
        report = obs.attribute(tel, trace)
        assert report.n_matched >= 5
        assert not report.unmatched_records
        assert not report.unmatched_spans
        assert all(a.byte_exact for a in report.matched)
        buckets = report.buckets()
        assert len(buckets) == plan.n_buckets
        for a in report.matched:
            assert a.bytes_on_wire and a.bytes_on_wire > 0
            assert a.achieved_bytes_per_sec is not None
            assert a.achieved_bytes_per_sec > 0
        assert report.total_achieved_bytes_per_sec() > 0

    def test_byteless_span_cannot_steal_a_byte_exact_record(self):
        """Review regression: byte-exact pairs are resolved for ALL
        spans before the order fallback — an earlier bytes-less span
        must not consume the record a later span matches exactly."""
        from chainermn_tpu.analysis import CollectiveRecord, CollectiveTrace

        def rec(payload):
            return CollectiveRecord(
                primitive="psum", cls="all_reduce", axes=("mn",),
                dtypes=("float32",), shapes=((payload // 4,),),
                context=(), axis_sizes=(2,), payload_bytes=payload,
                bytes_on_wire=payload,
            )

        trace = CollectiveTrace(records=(rec(400), rec(100)))
        with obs.observe() as tel:
            with obs.span("collective.allreduce", bytes=None):
                pass
            with obs.span("collective.psum", bucket=0, bytes=400):
                pass
        report = obs.attribute(tel, trace)
        by_name = {a.span_name: a for a in report.matched}
        psum = by_name["collective.psum"]
        assert psum.byte_exact and psum.record.payload_bytes == 400
        fallback = by_name["collective.allreduce"]
        assert not fallback.byte_exact
        assert fallback.record.payload_bytes == 100
        assert not report.unmatched_records

    def test_unmatched_sides_are_reported(self):
        """A span with no record of its class, and records no span
        measured, land in the report's unmatched lists — never
        silently dropped."""
        from chainermn_tpu.analysis import trace_collectives
        from chainermn_tpu.functions.collectives import pmean
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:2]), ("mn",))

        def f(x):
            return pmean(x, "mn")

        body = jax.shard_map(
            f, mesh=mesh,
            in_specs=jax.sharding.PartitionSpec("mn"),
            out_specs=jax.sharding.PartitionSpec("mn"),
            check_vma=False,
        )
        trace = trace_collectives(body, jnp.zeros((2, 4)))
        assert trace.count("all_reduce") >= 1
        with obs.observe() as tel:
            with obs.span("collective.alltoall", bytes=128):
                pass
        report = obs.attribute(tel, trace)
        assert report.n_matched == 0
        assert len(report.unmatched_spans) == 1
        assert len(report.unmatched_records) == len(trace.records)


# ----------------------------------------------------------------------
# MetricsReport
# ----------------------------------------------------------------------
class TestMetricsReport:
    def test_rows_and_jsonl_diffable_by_perf_history(
        self, comm, tmp_path
    ):
        trainer = _mlp_trainer(comm)
        rep = obs.MetricsReport(
            comm, trigger=(1, "iteration"), out=str(tmp_path),
            filename="metrics.jsonl",
        )
        trainer.extend(rep)
        with obs.observe():
            trainer.run()
        assert rep.last_report is not None
        rows = rep.last_report["rows"]
        phases = {r["phase"] for r in rows}
        assert "step" in phases and "update" in phases
        for r in rows:
            assert r["p50_ms"] > 0 and r["p99_ms"] >= r["p50_ms"]
            assert r["n_measurements"] >= 1
        # single-controller world: one process, nobody to straggle
        assert rep.last_report["stragglers"] == []
        # the JSONL rows load as perf_history pseudo-metrics
        lines = [json.loads(l)
                 for l in open(tmp_path / "metrics.jsonl")]
        assert all("phase" in l for l in lines)
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ), "benchmarks"))
        import perf_history as ph
        capture = tmp_path / "cap.json"
        capture.write_text(json.dumps({
            "tail": "\n".join(json.dumps(l) for l in lines)
        }))
        loaded = ph.load_rows(str(capture))
        assert any(k.startswith("phase.step.") for k in loaded)
        assert ph.lower_is_better(
            "phase.step.p50_ms", loaded["phase.step.p50_ms"]
        )

    def test_report_enables_own_telemetry_when_none_active(self, comm):
        trainer = _mlp_trainer(comm)
        rep = obs.MetricsReport(
            comm, trigger=(1, "iteration"), filename=None
        )
        trainer.extend(rep)
        assert obs.active() is None
        trainer.run()
        assert obs.active() is None  # finalize uninstalled it
        assert rep.last_report is not None
        assert rep.last_report["rows"]

    def test_straggler_flagged_from_synthetic_summaries(self):
        """The cross-rank rule in isolation: process 1's mean step time
        3x the median -> flagged, event emitted."""
        rep = obs.MetricsReport(comm=None, straggler_factor=1.5)
        by_proc = {
            0: {"process": 0, "phases": {"step": [0.01, 0.011]}},
            1: {"process": 1, "phases": {"step": [0.03, 0.032]}},
        }

        class _T:
            iteration = 7
            observation = {}

        sink = ResilienceLog()
        attach(sink)
        try:
            rep._flag_stragglers(by_proc, _T())
        finally:
            detach(sink)
        assert rep.straggler_processes == [1]
        evs = sink.events("straggler")
        assert len(evs) == 1
        assert evs[0].info["process"] == 1
        assert evs[0].info["ratio"] > 1.4

    def test_no_straggler_when_balanced(self):
        rep = obs.MetricsReport(comm=None)
        by_proc = {
            0: {"process": 0, "phases": {"step": [0.01]}},
            1: {"process": 1, "phases": {"step": [0.011]}},
        }

        class _T:
            iteration = 1
            observation = {}

        rep._flag_stragglers(by_proc, _T())
        assert rep.straggler_processes == []

    def test_lockstep_straggler_convicted_by_host_phase(self):
        """The real-world shape: lockstep SPMD equalizes wall-clock
        step time (the healthy rank blocks in the collective), so the
        convicting evidence is the rank-LOCAL update.host phase."""
        rep = obs.MetricsReport(comm=None)
        by_proc = {
            0: {"process": 0, "phases": {
                "step": [0.255], "update.host": [0.0001],
            }},
            1: {"process": 1, "phases": {
                "step": [0.262], "update.host": [0.250],
            }},
        }

        class _T:
            iteration = 6
            observation = {}

        sink = ResilienceLog()
        attach(sink)
        try:
            rep._flag_stragglers(by_proc, _T())
        finally:
            detach(sink)
        assert rep.straggler_processes == [1]
        ev = sink.events("straggler")[0]
        assert ev.info["phase"] == "update.host"

    def test_materiality_floor_ignores_bookkeeping_noise(self):
        """A 4x ratio on a 20-MICROsecond host phase is noise, not a
        straggler: below min_step_fraction of step time it cannot
        convict."""
        rep = obs.MetricsReport(comm=None)
        by_proc = {
            0: {"process": 0, "phases": {
                "step": [0.25], "update.host": [0.00002],
            }},
            1: {"process": 1, "phases": {
                "step": [0.25], "update.host": [0.00008],
            }},
        }

        class _T:
            iteration = 1
            observation = {}

        rep._flag_stragglers(by_proc, _T())
        assert rep.straggler_processes == []

    def test_windows_are_incremental(self, comm):
        """Each report summarizes only the NEW samples since the last
        one (a late straggler cannot be averaged away)."""
        rep = obs.MetricsReport(comm=None, phases=("p",))
        with obs.observe() as tel:
            tel.registry.histogram("p").extend([1.0, 2.0])
            s1 = rep._local_summary()
            tel.registry.histogram("p").observe(9.0)
            s2 = rep._local_summary()
        assert s1["phases"]["p"] == [1.0, 2.0]
        assert s2["phases"]["p"] == [9.0]

    def test_no_step_baseline_refuses_to_convict(self):
        """Review regression: without a recorded step phase the
        materiality floor is undefined — a non-step phase must then
        never convict (floor=0 would re-admit microsecond noise)."""
        rep = obs.MetricsReport(comm=None, phases=("data.wait",))
        by_proc = {
            0: {"process": 0, "phases": {"data.wait": [0.000015]}},
            1: {"process": 1, "phases": {"data.wait": [0.000030]}},
        }

        class _T:
            iteration = 1
            observation = {}

        rep._flag_stragglers(by_proc, _T())
        assert rep.straggler_processes == []

    def test_straggler_factor_validated(self):
        with pytest.raises(ValueError):
            obs.MetricsReport(straggler_factor=1.0)

    def test_failed_exchange_rolls_back_the_window(self):
        """Review regression: a retry-exhausted exchange must not
        consume the window's samples — the next report still covers
        the interval that contained the faults."""

        class _BadComm:
            process_index = 0
            process_count = 2

            def allgather_obj(self, obj):
                raise RuntimeError("exchange down")

        rep = obs.MetricsReport(_BadComm(), phases=("p",))

        class _T:
            iteration = 3
            observation = {}

        with obs.observe() as tel:
            tel.registry.histogram("p").extend([1.0, 2.0])
            with pytest.raises(RuntimeError):
                rep(_T())
            # the samples survived for the next report
            assert rep._local_summary()["phases"]["p"] == [1.0, 2.0]

    def test_finalize_isolated_per_extension(self, comm):
        """Review regression: one raising finalize must neither mask
        the others (later cleanups still run) nor vanish on a clean
        run (the first failure is re-raised)."""
        trainer = _mlp_trainer(comm)
        ran = []

        class _Boom:
            name = "boom"
            trigger = (1000, "iteration")

            def __call__(self, t):
                pass

            def finalize(self, t=None):
                ran.append("boom")
                raise RuntimeError("finalize failed")

        class _After:
            name = "after"
            trigger = (1000, "iteration")

            def __call__(self, t):
                pass

            def finalize(self, t=None):
                ran.append("after")

        trainer.extend(_Boom())
        trainer.extend(_After())
        with pytest.raises(RuntimeError, match="finalize failed"):
            trainer.run()
        assert ran == ["boom", "after"]  # later finalize still ran
        assert trainer.resilience_log.counts.get("finalize_error") == 1


# ----------------------------------------------------------------------
# time_steps satellite
# ----------------------------------------------------------------------
class TestTimeStepsSamples:
    def test_returns_samples_per_repeat(self):
        calls = []

        def run():
            calls.append(1)
            return np.zeros((1,))

        dt, samples = time_steps(run, steps=2, warmup=1, repeats=3)
        assert len(samples) == 3
        assert dt > 0 or dt == samples[-1]
        # protocol fields derive from the SAME samples
        pf = protocol_fields(samples)
        assert pf["n_measurements"] == 3

    def test_reported_dt_is_min_positive_sample(self):
        def run():
            return np.zeros((1,))

        dt, samples = time_steps(run, steps=1, warmup=1, repeats=4)
        pos = [s for s in samples if s > 0]
        if pos:
            assert dt == min(pos)

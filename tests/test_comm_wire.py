"""Gradient wire tests: bucketed fused allreduce + compressed codecs.

ISSUE 4 tentpole pins, in order of load-bearingness:

* the compiled ResNet-50 train step lowers to <= 8 ``all-reduce`` HLO
  ops under the default bucket plan (vs one per gradient leaf — 267 —
  before the wire layer), counted in the lowered StableHLO text the
  same way PR 2's ``block_census`` pinned the kernel taxonomy;
* the uncompressed bucketed sync is BIT-IDENTICAL to the per-leaf path
  (flatten order is tree-flatten order, reduction is elementwise, so
  grouping changes neither the summands nor their rank order) —
  asserted at 0 tolerance;
* int8 wire + error feedback converges to within 1% of fp32 sync on
  the MLP tier over 200 steps;
* the bucket plan is a pure function of shapes (deterministic across
  processes — same shapes, same hash);
* the reduced-precision mean divides AFTER casting off the wire: the
  old ``psum(g.astype(bf16)) / n`` order rounded the mean to bf16 for
  no wire-byte saving; the ULP test below constructs a mean that the
  old order misses by a full bf16 ULP and the new order hits exactly.
"""

import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

import chainermn_tpu as cmn
from chainermn_tpu import comm_wire as cw
from chainermn_tpu.comm_wire import (
    WireConfig,
    WirePlanMismatchError,
    codec_of_dtype,
    flatten_to_buckets,
    make_plan,
    plan_agreement,
    plan_of_tree,
    resolve_wire,
    storage_dtype,
    unflatten_from_buckets,
    zero_residuals,
)
from chainermn_tpu.optimizers import build_train_step


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("tpu", devices=devices8)


def _assert_tree_bit_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        assert jnp.dtype(x.dtype) == jnp.dtype(y.dtype)
        np.testing.assert_array_equal(
            np.asarray(x, np.float64) if x.dtype == jnp.bfloat16
            else np.asarray(x),
            np.asarray(y, np.float64) if y.dtype == jnp.bfloat16
            else np.asarray(y),
        )


# ----------------------------------------------------------------------
# planner: plan shape, determinism, round trip
# ----------------------------------------------------------------------
def _mixed_tree():
    rng = np.random.RandomState(7)
    return {
        "a": {
            "w": jnp.asarray(rng.randn(3, 4), jnp.float32),
            "b": jnp.asarray(rng.randn(7), jnp.bfloat16),
        },
        "scalar": jnp.asarray(1.25, jnp.float32),
        "ints": jnp.asarray(rng.randint(0, 100, (2, 2)), jnp.int32),
        "more": [
            jnp.asarray(rng.randn(5, 5), jnp.float32),
            jnp.asarray(rng.randn(6), jnp.bfloat16),
        ],
    }


class TestPlanner:
    def test_round_trip_mixed_dtypes_bit_exact(self):
        tree = _mixed_tree()
        plan = plan_of_tree(tree)
        buckets = flatten_to_buckets(plan, tree)
        out = unflatten_from_buckets(plan, buckets, tree)
        _assert_tree_bit_equal(out, tree)

    def test_round_trip_scalar_leaf_only(self):
        tree = {"s": jnp.asarray(3.5, jnp.float32)}
        plan = plan_of_tree(tree)
        assert plan.n_leaves == 1 and plan.n_buckets == 1
        out = unflatten_from_buckets(
            plan, flatten_to_buckets(plan, tree), tree
        )
        _assert_tree_bit_equal(out, tree)

    def test_round_trip_empty_tree(self):
        plan = plan_of_tree({})
        assert plan.n_leaves == 0 and plan.n_buckets == 0
        assert flatten_to_buckets(plan, {}) == []
        assert unflatten_from_buckets(plan, [], {}) == {}

    def test_round_trip_tiny_buckets(self):
        # bucket_bytes=1: every leaf gets its own bucket, still exact
        tree = _mixed_tree()
        plan = plan_of_tree(tree, bucket_bytes=1, max_buckets=0)
        assert plan.n_buckets == plan.n_leaves
        out = unflatten_from_buckets(
            plan, flatten_to_buckets(plan, tree), tree
        )
        _assert_tree_bit_equal(out, tree)

    def test_buckets_are_dtype_homogeneous(self):
        plan = plan_of_tree(_mixed_tree(), bucket_bytes=64)
        leaves = jax.tree_util.tree_leaves(_mixed_tree())
        for b in plan.buckets:
            for s in b.slots:
                assert leaves[s.index].dtype == jnp.dtype(b.dtype)

    def test_slots_contiguous_in_flatten_order(self):
        plan = plan_of_tree(_mixed_tree(), bucket_bytes=1 << 30)
        for b in plan.buckets:
            off = 0
            last_index = -1
            for s in b.slots:
                assert s.offset == off
                assert s.index > last_index  # tree-flatten order
                off += s.size
                last_index = s.index
            assert off == b.size

    def test_every_leaf_covered_exactly_once(self):
        plan = plan_of_tree(_mixed_tree(), bucket_bytes=64)
        seen = sorted(
            s.index for b in plan.buckets for s in b.slots
        )
        assert seen == list(range(plan.n_leaves))

    def test_max_buckets_coalesces_upward(self):
        # 40 x 1KiB f32 leaves with a 1KiB target would be 40 buckets;
        # max_buckets=6 must coalesce to <= 6
        leaves = [jnp.zeros((256,), jnp.float32) for _ in range(40)]
        plan = make_plan(leaves, bucket_bytes=1024, max_buckets=6)
        assert plan.n_buckets <= 6
        unbounded = make_plan(leaves, bucket_bytes=1024, max_buckets=0)
        assert unbounded.n_buckets == 40

    def test_dtype_floor_beats_max_buckets(self):
        # 3 dtypes cannot fit in 2 buckets: the floor is one per dtype
        leaves = [
            jnp.zeros((4,), jnp.float32),
            jnp.zeros((4,), jnp.bfloat16),
            jnp.zeros((4,), jnp.int32),
        ]
        plan = make_plan(leaves, bucket_bytes=1, max_buckets=2)
        assert plan.n_buckets == 3

    def test_oversized_leaf_gets_own_bucket(self):
        leaves = [
            jnp.zeros((4,), jnp.float32),
            jnp.zeros((10_000,), jnp.float32),  # >> bucket_bytes
            jnp.zeros((4,), jnp.float32),
        ]
        plan = make_plan(leaves, bucket_bytes=64, max_buckets=0)
        sizes = sorted(len(b.slots) for b in plan.buckets)
        assert 10_000 in [b.size for b in plan.buckets]
        assert sizes.count(1) >= 1

    def test_plan_is_pure_function_of_shapes(self):
        # arrays vs ShapeDtypeStructs vs different VALUES: same plan hash
        tree = _mixed_tree()
        structs = jax.tree_util.tree_map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), tree
        )
        other_values = jax.tree_util.tree_map(
            lambda l: (l * 0 + 1).astype(l.dtype), tree
        )
        h = plan_of_tree(tree).plan_hash()
        assert plan_of_tree(structs).plan_hash() == h
        assert plan_of_tree(other_values).plan_hash() == h

    def test_plan_hash_changes_with_shapes_and_knobs(self):
        tree = _mixed_tree()
        h = plan_of_tree(tree).plan_hash()
        grown = dict(tree, extra=jnp.zeros((9,), jnp.float32))
        assert plan_of_tree(grown).plan_hash() != h
        assert plan_of_tree(tree, bucket_bytes=64).plan_hash() != h

    def test_leaf_count_mismatch_raises(self):
        tree = _mixed_tree()
        plan = plan_of_tree(tree)
        with pytest.raises(ValueError, match="leaves"):
            flatten_to_buckets(plan, {"just_one": jnp.zeros((3,))})
        with pytest.raises(ValueError, match="leaves"):
            unflatten_from_buckets(plan, [], {"just_one": jnp.zeros((3,))})

    def test_bad_bucket_bytes_rejected(self):
        with pytest.raises(ValueError, match="bucket_bytes"):
            make_plan([jnp.zeros((3,))], bucket_bytes=0)


# ----------------------------------------------------------------------
# codecs: config resolution + storage dtype
# ----------------------------------------------------------------------
class TestWireConfig:
    def test_unknown_codec_rejected(self):
        with pytest.raises(ValueError, match="codec"):
            WireConfig(codec="int4").validate()

    @pytest.mark.parametrize("codec", ["none", "f32"])
    def test_error_feedback_needs_lossy_codec(self, codec):
        with pytest.raises(ValueError, match="error_feedback"):
            WireConfig(codec=codec, error_feedback=True).validate()

    def test_codec_of_dtype_reference_parity(self):
        # the reference's PureNcclCommunicator(allreduce_grad_dtype=...)
        # knob maps onto codec names
        assert codec_of_dtype(None) == "none"
        assert codec_of_dtype(jnp.float16) == "f16"
        assert codec_of_dtype(jnp.bfloat16) == "bf16"
        assert codec_of_dtype(jnp.float32) == "f32"
        with pytest.raises(ValueError, match="int8"):
            codec_of_dtype(jnp.int8)

    def test_resolve_wire_forms(self, comm):
        assert resolve_wire("per_leaf", comm) is None
        assert resolve_wire(None, comm).codec == "none"
        assert resolve_wire("auto", comm).codec == "none"
        assert resolve_wire("int8", comm).codec == "int8"
        explicit = WireConfig(codec="bf16", bucket_bytes=123)
        assert resolve_wire(explicit, comm) == explicit
        with pytest.raises(ValueError, match="wire"):
            resolve_wire(42, comm)

    def test_resolve_wire_auto_follows_comm_dtype(self, devices8):
        c = cmn.create_communicator(
            "tpu", devices=devices8, allreduce_grad_dtype=jnp.bfloat16
        )
        assert resolve_wire("auto", c).codec == "bf16"

    def test_auto_falls_back_per_leaf_on_uncodeced_dtype(self, devices8):
        """An allreduce_grad_dtype with no wire codec (float64) worked
        as a bare per-leaf cast before the wire layer; the "auto"
        default must keep that working (legacy path) instead of raising
        at optimizer construction.  Only an explicit codec raises."""
        c = cmn.create_communicator(
            "tpu", devices=devices8, allreduce_grad_dtype="float64"
        )
        assert resolve_wire("auto", c) is None
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), c)
        assert opt.wire is None  # legacy per-leaf cast path
        with pytest.raises(ValueError, match="float64"):
            resolve_wire("float64", c)

    def test_storage_dtype_never_widens(self):
        # cast codecs store in the wire dtype (half the db state bytes)
        assert storage_dtype(
            WireConfig(codec="bf16"), jnp.float32
        ) == jnp.dtype(jnp.bfloat16)
        # ... unless that would WIDEN the gradient
        assert storage_dtype(
            WireConfig(codec="f32"), jnp.bfloat16
        ) == jnp.dtype(jnp.bfloat16)
        # none/int8 store natively (int8's scale is sync-time state)
        assert storage_dtype(
            WireConfig(codec="none"), jnp.float32
        ) == jnp.dtype(jnp.float32)
        assert storage_dtype(
            WireConfig(codec="int8"), jnp.float32
        ) == jnp.dtype(jnp.float32)

    def test_zero_residuals_match_plan_layout(self):
        tree = _mixed_tree()
        plan = plan_of_tree(tree)
        res = zero_residuals(plan, tree)
        assert len(res) == plan.n_buckets
        for r, b in zip(res, plan.buckets):
            assert r.shape == (b.size,)
            assert r.dtype == jnp.dtype(b.dtype)
            assert not np.any(np.asarray(r, np.float32))


# ----------------------------------------------------------------------
# compiled tier: bit identity + HLO collective census
# ----------------------------------------------------------------------
def _two_leaf_loss(params, batch):
    m = batch.mean(axis=0)
    return 0.5 * jnp.sum((params["a"] - m[:4]) ** 2) + 0.5 * jnp.sum(
        (params["b"] - m[4:].reshape(1, 3)) ** 2
    )


def _run_steps(comm, wire, n_steps=3, lr=0.7, dtype=None, db=False):
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(lr), comm, wire=wire, double_buffering=db
    )
    params = {"a": jnp.zeros((4,)), "b": jnp.zeros((1, 3))}
    step = build_train_step(comm, _two_leaf_loss, opt, donate=False)
    p, o = step.place(params, opt.init(params))
    x = jnp.asarray(
        np.random.RandomState(3).randn(8, 7), jnp.float32
    )
    bx = jax.device_put(x, step.batch_sharding)
    for _ in range(n_steps):
        p, o, _ = step(p, o, bx)
    return p


class TestBitIdentity:
    def test_uncompressed_bucketed_equals_per_leaf_exactly(self, comm):
        """Acceptance: f32 wire, 0 tolerance.  Within a bucket leaf data
        is concatenated in tree-flatten order; psum is elementwise, so
        grouping changes neither summands nor their rank order."""
        p_leaf = _run_steps(comm, "per_leaf")
        p_wire = _run_steps(comm, "auto")
        _assert_tree_bit_equal(p_leaf, p_wire)

    def test_bf16_wire_bucketed_equals_per_leaf_exactly(self, devices8):
        # cast codecs too: cast -> psum -> cast back -> /n runs the same
        # elementwise program either way
        c = cmn.create_communicator(
            "tpu", devices=devices8, allreduce_grad_dtype=jnp.bfloat16
        )
        p_leaf = _run_steps(c, "per_leaf")
        p_wire = _run_steps(c, "auto")
        _assert_tree_bit_equal(p_leaf, p_wire)

    def test_update_applies_mean_gradient_on_wire(self, comm):
        # the canonical TestGradientSync numbers, through the wire
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(1.0), comm, wire="auto"
        )
        params = {"w": jnp.zeros((4,))}

        def loss(p, b):
            return 0.5 * jnp.sum((p["w"] - b.mean(axis=0)) ** 2)

        step = build_train_step(comm, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        x = jnp.stack([jnp.full((4,), float(r)) for r in range(8)])
        p, _, _ = step(p, o, jax.device_put(x, step.batch_sharding))
        np.testing.assert_allclose(np.asarray(p["w"]), 3.5, rtol=1e-6)


class TestReducedPrecisionMeanULP:
    def test_divide_runs_off_the_wire(self, devices8):
        """Satellite: the mean divide happens AFTER casting back to the
        param dtype.  5 ranks contribute bf16-exact grads summing to 16;
        16/5 = 3.2 is NOT bf16-representable.  The fixed order returns
        float32(16)/5 (exact in f32); the old ``psum/n``-in-bf16 order
        returned bf16(3.2) = 3.203125 — one full bf16 ULP worse.  Both
        the per-leaf path and the bucketed wire must hit the f32 value
        bit-exactly."""
        c5 = cmn.create_communicator(
            "tpu", devices=devices8[:5], allreduce_grad_dtype=jnp.bfloat16
        )
        vals = np.asarray([1.0, 2.0, 3.0, 4.0, 6.0], np.float32)

        def loss(p, b):
            # one row per rank: local grad = w - row = vals[r] at w=0
            return 0.5 * jnp.sum((p["w"] - b.mean(axis=0)) ** 2)

        exact = np.float32(16.0) / np.float32(5.0)
        old_order = np.float32(
            jnp.asarray(16.0, jnp.bfloat16) / jnp.asarray(5, jnp.bfloat16)
        )
        assert old_order != exact  # the ULP gap this test pins

        for wire in ("per_leaf", "auto"):
            opt = cmn.create_multi_node_optimizer(
                optax.sgd(1.0), c5, wire=wire
            )
            params = {"w": jnp.zeros((2,))}
            step = build_train_step(comm=c5, loss_fn=loss, optimizer=opt,
                                    donate=False)
            p, o = step.place(params, opt.init(params))
            x = jnp.stack([jnp.full((2,), -v) for v in vals])
            p, _, _ = step(p, o, jax.device_put(x, step.batch_sharding))
            # sgd(1.0) from 0: w = -mean(grad) = +3.2 exactly, in f32
            np.testing.assert_array_equal(
                np.asarray(p["w"]), np.full((2,), -exact)
            )


def _count_all_reduce(step, p, o, batch):
    """Collective count via the STATIC analyzer (jaxpr walk — nothing
    lowers or compiles), which ISSUE 5 makes the source of truth for
    these pins; the HLO-text cross-check below keeps the walker honest
    against what XLA actually sees."""
    return step.collective_trace(p, o, batch).count("all_reduce")


class TestHLOCollectiveCensus:
    """Structural verification: the train step's all-reduce count equals
    bucket count + 1 (the loss pmean), not leaf count + 1.  Rewritten on
    the ISSUE 5 analyzer — the count pin reads the jaxpr walker's
    census, so the pin and the walk cannot drift apart — with ONE
    HLO-text cross-check retained (test_census_agrees_with_hlo_text)
    proving the walker counts the same program XLA lowers."""

    def _mnist_setup(self, comm, wire):
        from chainermn_tpu.models import MLP

        model = MLP(n_units=1000)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))

        def loss_fn(p, b):
            x, y = b
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x), y
            ).mean()

        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05), comm, wire=wire
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = (
            jax.device_put(jnp.zeros((64, 28, 28)), step.batch_sharding),
            jax.device_put(jnp.zeros((64,), jnp.int32),
                           step.batch_sharding),
        )
        return step, p, o, batch, params

    def test_mnist_bucketed_vs_per_leaf(self, comm):
        step, p, o, batch, params = self._mnist_setup(comm, "per_leaf")
        n_leaves = len(jax.tree_util.tree_leaves(params))
        assert _count_all_reduce(step, p, o, batch) == n_leaves + 1

        step, p, o, batch, params = self._mnist_setup(comm, "auto")
        plan = plan_of_tree(params)
        assert plan.n_buckets < n_leaves
        tr = step.collective_trace(p, o, batch)
        assert tr.count("all_reduce") == plan.n_buckets + 1
        # the MLP-tier budget pin: small trees still bucket (a bucketing
        # regression back to the leaf storm trips this, not just resnet)
        from chainermn_tpu.analysis import enforce

        enforce("mlp_train_step", tr)

    def test_census_agrees_with_hlo_text(self, comm):
        """The retained HLO-text cross-check: the jaxpr walker and a
        grep of the lowered StableHLO count the same all-reduces on the
        bucketed MNIST step — the two censuses verify each other, so a
        walker regression (missed sub-jaxpr) or a lowering surprise
        (GSPMD inserting a reduce) fails here."""
        from chainermn_tpu.analysis import assert_census_agreement

        step, p, o, batch, params = self._mnist_setup(comm, "auto")
        tr = step.collective_trace(p, o, batch)
        txt = step.get_jitted(p, o).lower(p, o, batch).as_text()
        n_text = len(re.findall(r"stablehlo\.all_reduce", txt))
        agreed = assert_census_agreement(tr, txt)
        assert agreed["all_reduce"] == n_text == tr.count("all_reduce")

    def test_mnist_int8_adds_exactly_one_scale_collective(self, comm):
        # the per-bucket absmax agreement is ONE batched pmax, not one
        # per bucket: buckets + pmax + loss pmean
        step, p, o, batch, params = self._mnist_setup(
            comm, WireConfig(codec="int8")
        )
        plan = plan_of_tree(params)
        assert _count_all_reduce(step, p, o, batch) == plan.n_buckets + 2

    def test_resnet50_lowers_to_at_most_8_all_reduces(self, comm):
        """Acceptance criterion: 267 gradient leaves -> default plan's
        4 buckets -> 5 all-reduce ops (4 grad buckets + loss pmean),
        enforced via the analyzer's pinned budget AND cross-checked
        against the lowered HLO text (ISSUE 5 acceptance: the walker
        agrees with the HLO census on the ResNet-50 step)."""
        from chainermn_tpu.analysis import assert_census_agreement, enforce
        from chainermn_tpu.models import ResNet50

        model = ResNet50(num_classes=1000, train=False)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))
        )
        n_leaves = len(jax.tree_util.tree_leaves(params))
        assert n_leaves > 200  # the leaf storm the wire replaces

        def loss_fn(p, b):
            x, y = b
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x), y
            ).mean()

        opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = (
            jax.device_put(jnp.zeros((8, 32, 32, 3)), step.batch_sharding),
            jax.device_put(jnp.zeros((8,), jnp.int32), step.batch_sharding),
        )
        tr = step.collective_trace(p, o, batch)
        n = tr.count("all_reduce")
        plan = plan_of_tree(params)
        assert n == plan.n_buckets + 1
        # the pinned budget (analysis.budgets): <= 8 all-reduce
        enforce("resnet50_train_step", tr)
        # the walker counts the same program XLA lowers
        txt = step.get_jitted(p, o).lower(p, o, batch).as_text()
        assert_census_agreement(tr, txt)


# ----------------------------------------------------------------------
# int8 + error feedback
# ----------------------------------------------------------------------
class TestInt8ErrorFeedback:
    def _mlp_run(self, comm, wire, n_steps, lr=0.05):
        rng = np.random.RandomState(0)
        w_true = rng.randn(8, 4).astype(np.float32)
        x = rng.randn(64, 8).astype(np.float32)
        y = x @ w_true
        params = {
            "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
            "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
        }

        def loss_fn(p, b):
            bx, by = b
            h = jnp.tanh(bx @ p["w1"])
            return jnp.mean((h @ p["w2"] - by) ** 2)

        opt = cmn.create_multi_node_optimizer(
            optax.sgd(lr), comm, wire=wire
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = (
            jax.device_put(jnp.asarray(x), step.batch_sharding),
            jax.device_put(jnp.asarray(y), step.batch_sharding),
        )
        loss = None
        for _ in range(n_steps):
            p, o, m = step(p, o, batch)
            loss = float(m["loss"])
        return loss, p, o

    def test_int8_ef_converges_with_fp32_equivalent_loss(self, comm):
        """Acceptance: int8 wire + error feedback matches fp32 sync
        within 1% training loss on the MLP tier over 200 steps."""
        l_fp32, _, _ = self._mlp_run(comm, "auto", 200)
        l_int8, _, _ = self._mlp_run(
            comm, WireConfig(codec="int8", error_feedback=True), 200
        )
        assert l_int8 <= l_fp32 * 1.01 + 1e-7, (
            f"int8+EF loss {l_int8} vs fp32 {l_fp32} exceeds 1%"
        )

    def test_error_feedback_residual_carried_in_state(self, comm):
        wire = WireConfig(codec="int8", error_feedback=True)
        _, _, o = self._mlp_run(comm, wire, 2)
        # state carries one flat residual per bucket, and quantization
        # of off-grid gradients leaves a nonzero residual behind
        res = o.wire_residual
        assert isinstance(res, tuple) and len(res) >= 1
        assert any(np.any(np.asarray(r) != 0) for r in res)

    def test_no_error_feedback_no_residual_state(self, comm):
        _, _, o = self._mlp_run(comm, WireConfig(codec="int8"), 2)
        assert o.wire_residual == ()

    def test_int8_mean_is_scale_correct(self, comm):
        # values exactly on the int8 grid reduce exactly: grads all
        # equal -> mean == the value (absmax scale maps it to +/-127)
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(1.0), comm, wire=WireConfig(codec="int8")
        )
        params = {"w": jnp.zeros((4,))}

        def loss(p, b):
            return 0.5 * jnp.sum((p["w"] - b.mean(axis=0)) ** 2)

        step = build_train_step(comm, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        x = jnp.full((8, 4), 2.0)  # same grad everywhere: w - 2
        p, _, _ = step(p, o, jax.device_put(x, step.batch_sharding))
        np.testing.assert_allclose(np.asarray(p["w"]), 2.0, rtol=1e-6)


# ----------------------------------------------------------------------
# composition: double buffering, ZeRO, config rejections
# ----------------------------------------------------------------------
class TestDoubleBufferingWire:
    def test_stale_grad_state_is_flat_buckets(self, comm):
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), comm, double_buffering=True,
            wire=WireConfig(codec="bf16"),
        )
        params = {"a": jnp.zeros((4,)), "b": jnp.zeros((1, 3))}
        state = opt.init(params)
        plan = plan_of_tree(params)
        assert isinstance(state.prev_grads, tuple)
        assert len(state.prev_grads) == plan.n_buckets
        # cast codec stores the stale buffer in the WIRE dtype — half
        # the state bytes, the same buffer the reference's swap held
        assert all(
            b.dtype == jnp.bfloat16 for b in state.prev_grads
        )

    def test_bucketed_db_matches_per_leaf_db_exactly(self, comm):
        p_leaf = _run_steps(comm, "per_leaf", db=True)
        p_wire = _run_steps(comm, "auto", db=True)
        _assert_tree_bit_equal(p_leaf, p_wire)

    def test_db_staleness_semantics_on_wire(self, comm):
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(1.0), comm, double_buffering=True, wire="auto"
        )
        params = {"w": jnp.zeros((2,))}

        def loss(p, b):
            return 0.5 * jnp.sum((p["w"] - b.mean(axis=0)) ** 2)

        step = build_train_step(comm, loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        x = jnp.stack([jnp.full((2,), float(r)) for r in range(8)])
        bx = jax.device_put(x, step.batch_sharding)
        p1, o, _ = step(p, o, bx)
        np.testing.assert_allclose(np.asarray(p1["w"]), 0.0, atol=1e-7)
        p2, o, _ = step(p1, o, bx)
        np.testing.assert_allclose(np.asarray(p2["w"]), 3.5, rtol=1e-6)


class TestZeroRedundancyWire:
    def test_bucketed_zero_matches_plain_adam(self, comm):
        params = {"w": jnp.ones((8,)) * 0.3, "v": jnp.ones((16,)) * -0.2}

        def loss(p, b):
            m = b.mean(axis=0)
            return 0.5 * jnp.sum((p["w"] - m[:8]) ** 2) + 0.5 * jnp.sum(
                (p["v"] - m[8:]) ** 2
            )

        def run(opt):
            step = build_train_step(comm, loss, opt, donate=False)
            p, o = step.place(params, opt.init(params))
            x = jnp.asarray(
                np.random.RandomState(5).randn(8, 24), jnp.float32
            )
            bx = jax.device_put(x, step.batch_sharding)
            for _ in range(3):
                p, o, _ = step(p, o, bx)
            return p

        p_plain = run(cmn.create_multi_node_optimizer(optax.adam(0.1), comm))
        p_zero = run(cmn.create_multi_node_optimizer(
            optax.adam(0.1), comm, zero_redundancy=True
        ))
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_plain[k]), np.asarray(p_zero[k]), rtol=1e-5
            )

    def test_int8_zero_rejected(self, comm):
        with pytest.raises(ValueError, match="int8"):
            cmn.create_multi_node_optimizer(
                optax.adam(0.1), comm, zero_redundancy=True, wire="int8"
            )

    def test_error_feedback_zero_rejected(self, comm):
        with pytest.raises(ValueError, match="error_feedback"):
            cmn.create_multi_node_optimizer(
                optax.adam(0.1), comm, zero_redundancy=True,
                wire=WireConfig(codec="bf16", error_feedback=True),
            )

    def test_error_feedback_double_buffering_rejected(self, comm):
        with pytest.raises(ValueError, match="error_feedback"):
            cmn.create_multi_node_optimizer(
                optax.adam(0.1), comm, double_buffering=True,
                wire=WireConfig(codec="bf16", error_feedback=True),
            )


# ----------------------------------------------------------------------
# eager tier: bucketed allreduce_grad on the stacked-array communicators
# ----------------------------------------------------------------------
class TestEagerBucketedAllreduce:
    def _stacked_tree(self, comm, seed=11):
        rng = np.random.RandomState(seed)
        return {
            "w": jnp.asarray(rng.randn(comm.size, 3, 4), jnp.float32),
            "b": jnp.asarray(rng.randn(comm.size, 5), jnp.float32),
        }

    def test_xla_bucketed_mean_matches_oracle(self, comm):
        grads = self._stacked_tree(comm)
        out = comm.allreduce_grad(grads)
        for k in grads:
            expect = np.asarray(grads[k]).mean(0)
            for r in range(comm.size):
                np.testing.assert_allclose(
                    np.asarray(out[k])[r], expect, rtol=1e-5
                )

    def test_noncudaaware_bucketed_mean_matches_oracle(self, devices8):
        # "non_cuda_aware", not "naive": NaiveCommunicator inherits the
        # per-leaf base allreduce_grad — only this name exercises the
        # host-staged bucketed path in variants.py
        c = cmn.create_communicator("non_cuda_aware", devices=devices8)
        grads = self._stacked_tree(c)
        out = c.allreduce_grad(grads)
        for k in grads:
            expect = np.asarray(grads[k]).mean(0)
            for r in range(c.size):
                np.testing.assert_allclose(
                    np.asarray(out[k])[r], expect, rtol=1e-5
                )

    def test_empty_tree_passthrough(self, comm):
        assert comm.allreduce_grad({}) == {}

    def test_sum_without_wire_dtype_is_bucketed(self, comm):
        """mean=False with no wire dtype rides the bucketed path too
        (it used to fall back to the per-leaf collective storm)."""
        grads = self._stacked_tree(comm)
        out = comm.allreduce_grad(grads, mean=False)
        for k in grads:
            expect = np.asarray(grads[k]).sum(0)
            for r in range(comm.size):
                np.testing.assert_allclose(
                    np.asarray(out[k])[r], expect, rtol=1e-5
                )

    def test_cast_dtype_sum_not_mean(self, devices8):
        """``mean=False`` with a wire dtype must return the SUM: the
        cast fn pair carries a true sum variant (the old single cast fn
        always divided, handing a mean to callers asking for a sum)."""
        c = cmn.create_communicator(
            "tpu", devices=devices8, allreduce_grad_dtype=jnp.bfloat16
        )
        rng = np.random.RandomState(3)
        # small integers: exactly representable in bf16, sums ≤ 32 are
        # exact too, so the oracle holds bit-for-bit despite the wire
        grads = {"w": jnp.asarray(
            rng.randint(0, 5, size=(c.size, 3, 4)), jnp.float32
        )}
        out = c.allreduce_grad(grads, mean=False)
        expect = np.asarray(grads["w"]).sum(0)
        for r in range(c.size):
            np.testing.assert_array_equal(np.asarray(out["w"])[r], expect)


# ----------------------------------------------------------------------
# the bench's pinned-profile resolution
# ----------------------------------------------------------------------
class TestPinnedProfileResolution:
    """``_pinned_profile``: the tuned rungs' pin-vs-calibrate decision.
    Review regression: a pinned path that stopped resolving silently
    demoted every capture to in-process calibration — fresh hash each
    run, every regression disclosed as RETUNED, the gate permanently
    off — so the MISSING-file case must say so on stderr.  A
    mesh-signature mismatch stays silent by design (one pinned file can
    only match one rung's mesh)."""

    @pytest.fixture()
    def bench(self):
        import os
        import sys

        repo = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        sys.path.insert(0, os.path.join(repo, "benchmarks"))
        try:
            import comm_overlap_bench as cob
        finally:
            sys.path.pop(0)
        return cob

    def _profile(self, mesh_axes):
        from chainermn_tpu.comm_wire import BandwidthProfile

        return BandwidthProfile(
            mesh_axes=mesh_axes,
            curves={("flat", "all_reduce"): ((1024, 1e9),
                                             (1 << 22, 1e9))},
            latency={"flat": 1e-4},
        )

    def test_unset_env_is_silent_none(self, bench, comm, monkeypatch,
                                      capsys):
        from chainermn_tpu.comm_wire import PROFILE_ENV

        monkeypatch.delenv(PROFILE_ENV, raising=False)
        assert bench._pinned_profile(comm.mesh) is None
        assert capsys.readouterr().err == ""

    def test_missing_pinned_path_discloses_on_stderr(self, bench, comm,
                                                     monkeypatch,
                                                     capsys):
        from chainermn_tpu.comm_wire import PROFILE_ENV

        monkeypatch.setenv(PROFILE_ENV, "/nonexistent/profile.json")
        assert bench._pinned_profile(comm.mesh) is None
        err = capsys.readouterr().err
        assert "does not exist" in err
        assert "retuned" in err

    def test_matching_pin_loads_and_mismatch_is_silent_none(
            self, bench, comm, monkeypatch, capsys, tmp_path):
        from chainermn_tpu.comm_wire import PROFILE_ENV

        good = self._profile((("mn", 8),))
        path = str(tmp_path / "pin.json")
        good.save(path)
        monkeypatch.setenv(PROFILE_ENV, path)
        got = bench._pinned_profile(comm.mesh)
        assert got is not None
        assert got.profile_hash() == good.profile_hash()
        # a pin for some OTHER mesh: fresh-calibration fallback, silent
        other = self._profile((("mn_inter", 2), ("mn_intra", 4)))
        other.save(path)
        assert bench._pinned_profile(comm.mesh) is None
        assert capsys.readouterr().err == ""


# ----------------------------------------------------------------------
# wire_* bench rungs: CI smoke on the CPU mesh
# ----------------------------------------------------------------------
class TestWireBenchRungsCI:
    def test_wire_rungs_emit_protocol_json_on_cpu_mesh(self, tmp_path):
        """Acceptance: the ``wire_*`` rungs of comm_overlap_bench.py run
        on the 8-virtual-device CPU mesh and print per-rung JSON carrying
        the min-of-N protocol fields (``n_measurements``/
        ``spread_max_over_min``) plus the wire provenance
        (``wire_codec``/``wire_buckets``) — measurement-ready for the
        next TPU capture.  Tiny shapes via the HUNT_* knobs so this is
        a smoke of the harness, not a measurement."""
        import json as _json
        import os
        import subprocess
        import sys

        from conftest import subprocess_env

        from chainermn_tpu.comm_wire import BandwidthProfile, PROFILE_ENV

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # a PINNED profile for the flat (mn, 8) mesh: the wire_tuned
        # rung must prefer it (stable hash -> perf_history can GATE the
        # row), while the hier rung's mesh signature mismatches and
        # falls back to in-process calibration (fresh hash -> disclosed
        # retune)
        pinned = BandwidthProfile(
            mesh_axes=(("mn", 8),),
            curves={("flat", "all_reduce"): ((1024, 1e8), (1 << 22, 1e9)),
                    ("flat", "reduce_scatter"): ((1024, 1e8),
                                                 (1 << 22, 1e9)),
                    ("flat", "all_gather"): ((1024, 1e8), (1 << 22, 1e9))},
            latency={"flat": 1e-4}, label="ci_pinned",
        )
        pinned_path = str(tmp_path / "pinned_profile.json")
        pinned.save(pinned_path)
        env = subprocess_env(8)
        env.update({"HUNT_MLP_UNITS": "32", "HUNT_MLP_BATCH": "8",
                    "HUNT_K": "4", "HUNT_REPEATS": "2",
                    "HUNT_CAL_SIZES": "4096,65536",
                    PROFILE_ENV: pinned_path})
        # one subprocess covers the PR 3 wire ladder, the ISSUE 11
        # multi-hop schedule rungs (wire_flat/wire_hier/wire_hier_int8
        # run on a hierarchical mesh of 2 synthetic slices — the bench
        # sets CHAINERMN_TPU_FAKE_SLICE_SIZE itself under --cpu-mesh)
        # AND the ISSUE 12 measured-autotune rungs (wire_tuned runs an
        # in-process calibration sweep, sizes kept tiny via
        # HUNT_CAL_SIZES)
        rungs = ["wire_perleaf_sync", "wire_bucketed_sync",
                 "wire_int8_sync",
                 "wire_flat", "wire_hier", "wire_hier_int8",
                 "wire_tuned_base", "wire_tuned", "wire_tuned_hier"]
        proc = subprocess.run(
            [sys.executable,
             os.path.join(repo, "benchmarks", "comm_overlap_bench.py"),
             "--cpu-mesh", *rungs],
            env=env, capture_output=True, text=True, timeout=560,
            cwd=tmp_path,
        )
        assert proc.returncode == 0, (
            f"comm_overlap_bench exited {proc.returncode}\n"
            f"--- stdout ---\n{proc.stdout[-3000:]}\n"
            f"--- stderr ---\n{proc.stderr[-3000:]}"
        )
        recs = {}
        for line in proc.stdout.splitlines():
            if line.startswith("{"):
                r = _json.loads(line)
                if "variant" in r:
                    recs[r["variant"]] = r
        assert set(rungs) <= set(recs), (rungs, sorted(recs))
        for name in rungs:
            r = recs[name]
            assert r["n_measurements"] >= 2, r
            # spread needs >= 2 POSITIVE paired samples; on the noisy
            # CPU mesh a sample can land non-positive — the protocol
            # then omits the field honestly rather than fabricating it
            if len([s for s in r["samples_ms"] if s > 0]) >= 2:
                assert "spread_max_over_min" in r, r
        assert recs["wire_perleaf_sync"]["wire_codec"] == "per_leaf"
        assert "wire_buckets" not in recs["wire_perleaf_sync"]
        assert recs["wire_bucketed_sync"]["wire_codec"] == "none"
        assert recs["wire_bucketed_sync"]["wire_buckets"] >= 1
        assert recs["wire_int8_sync"]["wire_codec"] == "int8"
        # the leaf storm the bucket plan replaces, in numbers
        assert (recs["wire_bucketed_sync"]["wire_buckets"]
                < recs["wire_perleaf_sync"]["wire_n_leaves"])
        # ISSUE 11 rungs: schedule/codec fingerprints on a genuinely
        # factorized (2, 4) hierarchical mesh — wire_flat pins the
        # single-psum baseline, wire_hier/_int8 the staged program
        for name in ("wire_flat", "wire_hier", "wire_hier_int8"):
            assert recs[name]["mesh_shape"] == {
                "mn_inter": 2, "mn_intra": 4,
            }, recs[name]
            assert "wire_plan_hash" in recs[name]
        assert recs["wire_flat"]["wire_schedules"] == {
            "flat": recs["wire_flat"]["wire_buckets"]
        }
        assert recs["wire_hier"]["wire_schedules"] == {
            "hier_rs_ag": recs["wire_hier"]["wire_buckets"]
        }
        assert recs["wire_hier_int8"]["wire_codec"] == "int8"
        assert recs["wire_hier_int8"]["wire_schedules"] == {
            "hier_rs_ag": recs["wire_hier_int8"]["wire_buckets"]
        }
        # same layout, different schedule => different agreed plan hash
        assert (recs["wire_flat"]["wire_plan_hash"]
                != recs["wire_hier"]["wire_plan_hash"])
        # ISSUE 12 rungs: the tuned legs carry full provenance — the
        # profile content hash, the tuner's chosen knobs, and a plan
        # hash that differs from the untuned leg's (the profile hash
        # is folded in); the fixed-constant base leg carries none
        assert "profile_hash" not in recs["wire_tuned_base"]
        for name in ("wire_tuned", "wire_tuned_hier"):
            r = recs[name]
            assert r["profile_hash"], r
            assert r["tuned_max_buckets"] >= 1, r
            assert r["tuned_bucket_bytes"] >= 1, r
            assert r["wire_schedules"], r
            assert r["predicted_sync_ms"] > 0, r
        assert (recs["wire_tuned"]["wire_plan_hash"]
                != recs["wire_tuned_base"]["wire_plan_hash"])
        assert recs["wire_tuned_hier"]["mesh_shape"] == {
            "mn_inter": 2, "mn_intra": 4,
        }
        # pinned-vs-fresh provenance: the flat rung used the env
        # profile (hash stable -> gateable), the hier rung's mesh
        # mismatched it and calibrated fresh (hash differs -> retune
        # disclosure path)
        assert recs["wire_tuned"]["profile_hash"] \
            == pinned.profile_hash()[:12]
        assert recs["wire_tuned_hier"]["profile_hash"] \
            != pinned.profile_hash()[:12]


# ----------------------------------------------------------------------
# cross-process plan agreement
# ----------------------------------------------------------------------
class TestPlanAgreement:
    def test_agreement_on_real_communicator(self, comm):
        plan = plan_of_tree(_mixed_tree())
        assert plan_agreement(comm, plan) == plan.plan_hash()

    def test_truncated_payload_is_retried_in_lockstep(self, comm):
        """The mp satellite's single-controller half: a truncated
        exchange payload surfaces as PayloadCorruptionError on EVERY
        rank, plan_agreement retries the whole exchange, and the run
        completes (the 2-process version lives in mp_worker.py's
        wire_int8 scenario)."""
        from chainermn_tpu.resilience.fault_injection import (
            FaultSpec, inject_faults,
        )

        plan = plan_of_tree(_mixed_tree())
        with inject_faults(
            [FaultSpec("obj_store.exchange", "truncate", at=[1],
                       truncate_to=4)]
        ) as inj:
            assert plan_agreement(comm, plan) == plan.plan_hash()
        assert inj.log.counts.get("fault_injected", 0) >= 1

    def test_mismatch_raises(self):
        class FakeComm:
            def allgather_obj(self, h):
                return [h, "a-divergent-plan-hash"]

        plan = plan_of_tree(_mixed_tree())
        with pytest.raises(WirePlanMismatchError, match="mismatch"):
            plan_agreement(FakeComm(), plan)

    class _DivergentComm:
        """Multi-process comm whose world disagrees on the plan."""

        process_count = 2
        allreduce_grad_dtype = None
        axis_names = ("mn",)

        def allgather_obj(self, h):
            return [h, "a-divergent-plan-hash"]

    def test_optimizer_init_guards_plan_in_multi_process_world(self):
        """The guard is production-wired, not opt-in: ``init`` on a
        multi-process world exchanges the plan hash and fails loudly on
        divergence — BEFORE the first bucketed collective can deadlock
        or silently mix wire layouts."""
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), self._DivergentComm()
        )
        with pytest.raises(WirePlanMismatchError, match="mismatch"):
            opt.init(_mixed_tree())

    def test_init_guard_skips_under_tracing(self):
        """Traced init (eval_shape/jit) cannot run an eager obj
        exchange — the guard steps aside instead of crashing."""
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), self._DivergentComm()
        )
        state = jax.eval_shape(opt.init, _mixed_tree())
        assert state is not None

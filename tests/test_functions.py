"""Model-parallel function tests.

Parity: ``functions_tests/test_point_to_point_communication.py``,
``test_collective_communication.py``, ``test_pseudo_connect.py`` — forward
values + backward gradients across real shards.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from chainermn_tpu import functions as F


def _shmap(f, mesh, n_in=1, out_spec=P("mn")):
    return jax.jit(
        jax.shard_map(
            f, mesh=mesh, in_specs=tuple([P("mn")] * n_in),
            out_specs=out_spec, check_vma=False,
        )
    )


class TestPointToPoint:
    def test_send_moves_value(self, mesh8):
        f = _shmap(lambda x: F.send(x, "mn", dest=5, source=2), mesh8)
        x = jnp.arange(8.0).reshape(8, 1)
        out = np.asarray(f(x))
        assert out[5, 0] == 2.0
        assert out.sum() == 2.0

    def test_send_gradient_flows_back(self, mesh8):
        """Cotangent at dest must arrive at source (parity: Send.backward
        = recv of grad)."""

        def loss(x):
            y = F.send(x, "mn", dest=6, source=1)
            # per-shard loss: only rank 6's received payload counts, so the
            # global objective is counted exactly once and the cotangent
            # must ride the transpose ppermute back to rank 1
            idx = lax.axis_index("mn")
            return jnp.where(idx == 6, jnp.sum(y * 3.0), 0.0)

        g_f = _shmap(jax.grad(loss), mesh8)
        g = np.asarray(g_f(jnp.ones((8, 4))))
        np.testing.assert_allclose(g[1], 3.0)
        assert np.abs(g[[0, 2, 3, 4, 5, 6, 7]]).sum() == 0

    def test_exchange_ring(self, mesh8):
        f = _shmap(lambda x: F.exchange(x, "mn", shift=1), mesh8)
        x = jnp.arange(8.0).reshape(8, 1)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out[:, 0], np.roll(np.arange(8.0), 1))

    def test_pseudo_connect_value_and_grad(self):
        delegate = jnp.ones((3,))
        actual = jnp.arange(4.0)
        out = F.pseudo_connect(delegate, actual)
        np.testing.assert_allclose(np.asarray(out), np.arange(4.0))

        g = jax.grad(
            lambda d: jnp.sum(F.pseudo_connect(d, actual) ** 2)
        )(delegate)
        np.testing.assert_allclose(np.asarray(g), 0.0)


class TestCollectiveFunctions:
    def test_all_gather_and_transpose_grad(self, mesh8):
        f = _shmap(lambda x: F.all_gather(x, "mn"), mesh8, out_spec=P())
        x = jnp.arange(8.0).reshape(8, 1)
        np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x))

        def loss(x):
            g = F.all_gather(x, "mn")  # (8, 1) on every shard
            # count the objective once (on shard 0 only) so the gathered
            # cotangent reduce-scatters back to each owner exactly once
            idx = lax.axis_index("mn")
            return jnp.where(
                idx == 0, jnp.sum(g * jnp.arange(8.0)[:, None]), 0.0
            )

        grad_f = _shmap(jax.grad(loss), mesh8)
        g = np.asarray(grad_f(x))
        np.testing.assert_allclose(g[:, 0], np.arange(8.0), rtol=1e-6)

    def test_bcast_and_grad_sums_to_root(self, mesh8):
        f = _shmap(lambda x: F.bcast(x, "mn", root=3), mesh8)
        x = jnp.arange(8.0).reshape(8, 1)
        np.testing.assert_allclose(np.asarray(f(x)), 3.0)

        def loss(x):
            y = F.bcast(x, "mn", root=3)
            return jnp.sum(y)  # every shard contributes its received copy

        grad_f = _shmap(jax.grad(loss), mesh8)
        g = np.asarray(grad_f(x))
        # 8 shards each received x_3; total derivative at root = 8
        np.testing.assert_allclose(g[3, 0], 8.0)
        assert np.abs(g[np.arange(8) != 3]).sum() == 0

    def test_all_to_all(self, mesh8):
        # Layout semantics: per-shard (1, 8, 1) -> (8, 1, 1); reassembling
        # the received stacks along axis 1 (out_spec P(None, 'mn')) lands
        # global[a, b] = shard b's block from shard a = x[a, b] — i.e. the
        # exchange composed with this layout is the identity, while the
        # *per-shard* contents are the transposed row (shard j now holds
        # x[:, j]).  The eager `comm.alltoall` covers the transpose view.
        f = _shmap(
            lambda x: F.all_to_all(x, "mn", split_axis=1, concat_axis=0),
            mesh8, out_spec=P(None, "mn"),
        )
        x = jnp.arange(64.0).reshape(8, 8, 1)
        out = np.asarray(f(x))
        np.testing.assert_allclose(out, np.asarray(x))

    def test_scatter_roundtrip(self, mesh8):
        def f(x):
            mine = F.scatter(x, "mn", root=0, axis=0)
            return F.all_gather(mine, "mn", axis=0)

        g = _shmap(f, mesh8, out_spec=P())
        # every shard holds the same (8, 2) "root payload"
        payload = jnp.arange(16.0).reshape(8, 2)
        x = jnp.broadcast_to(payload, (8, 8, 2)).reshape(8, 8, 2)
        out = np.asarray(jax.jit(jax.shard_map(
            f, mesh=mesh8, in_specs=(P(None, None),), out_specs=P(),
            check_vma=False,
        ))(payload))
        np.testing.assert_allclose(out, np.asarray(payload))

    def test_reduce_scatter(self, mesh8):
        f = _shmap(
            lambda x: F.reduce_scatter(jnp.squeeze(x, 0), "mn")[None],
            mesh8,
        )
        x = jnp.ones((8, 16))
        out = np.asarray(f(x))
        assert out.shape == (8, 2)
        np.testing.assert_allclose(out, 8.0)

"""Example-script smoke tests — the user-facing CLI surface.

The reference's examples ARE its integration suite (`mpiexec -n N
python train_*.py`, SURVEY.md section 2 #33-35); these tests run each
shipped script end-to-end as a subprocess on a virtual CPU mesh with
tiny shapes, asserting it exits cleanly and reaches its final report.
Slower than unit tests (each subprocess compiles its programs) but they
are the only coverage of the argparse wiring, device selection, and
training-loop assembly the docs tell users to copy.
"""

import os
import subprocess
import sys

from conftest import subprocess_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "examples")


def _run(script, *args, tmp_path, devices=8, timeout=420):
    env = subprocess_env(devices)
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), *args],
        cwd=tmp_path, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{script} exited {proc.returncode}\n--- stdout ---\n"
        f"{proc.stdout[-3000:]}\n--- stderr ---\n{proc.stderr[-3000:]}"
    )
    return proc.stdout


class TestExampleScripts:
    def test_mnist_data_parallel(self, tmp_path):
        out = _run(
            "mnist/train_mnist.py", "--cpu-mesh", "--epoch", "1",
            "--n-train", "1024", "--n-test", "256", "--unit", "64",
            tmp_path=tmp_path,
        )
        assert "final:" in out and "loss" in out

    def test_mnist_model_parallel(self, tmp_path):
        out = _run(
            "mnist/train_mnist_model_parallel.py", "--cpu-mesh",
            "--epoch", "1", "--n-train", "512", "--n-test", "128",
            "--unit", "64", "--batchsize", "64", tmp_path=tmp_path,
        )
        assert "loss" in out

    def test_mnist_hybrid_dp_tp(self, tmp_path):
        out = _run(
            "mnist/train_mnist_hybrid.py", "--cpu-mesh", "--epoch", "1",
            "--n-train", "512", "--n-test", "128", "--unit", "64",
            "--batchsize", "64", "--tp", "2", tmp_path=tmp_path,
        )
        assert "loss" in out

    def test_imagenet_synthetic(self, tmp_path):
        out = _run(
            "imagenet/train_imagenet.py", "--cpu-mesh", "--epoch", "1",
            "--arch", "resnet18", "--image-size", "32",
            "--num-classes", "8", "--n-train", "64", "--n-val", "32",
            "--batchsize", "16", tmp_path=tmp_path,
        )
        assert "final:" in out

    def test_imagenet_native_uint8_wire(self, tmp_path):
        """The end-to-end uint8-wire path (VERDICT r4 #2): C++ loader
        ships raw uint8 crops, device_normalize runs inside the jitted
        step; training must still converge to a printed final record."""
        from chainermn_tpu.utils.native_loader import native_available

        if not native_available():
            pytest.skip("no C++ toolchain for the native loader")
        out = _run(
            "imagenet/train_imagenet.py", "--cpu-mesh", "--epoch", "1",
            "--arch", "resnet18", "--image-size", "32",
            "--num-classes", "8", "--n-train", "64", "--n-val", "32",
            "--batchsize", "16", "--native-loader",
            "--native-wire", "uint8", tmp_path=tmp_path,
        )
        assert "final:" in out

    def test_seq2seq(self, tmp_path):
        out = _run(
            "seq2seq/seq2seq.py", "--cpu-mesh", "--epoch", "1",
            "--n-train", "256", "--n-test", "64", "--unit", "32",
            "--batchsize", "32", tmp_path=tmp_path,
        )
        assert "final:" in out

    def test_seq2seq_model_parallel(self, tmp_path):
        # tiny dataset: the chain tier dispatches eagerly per stage, so
        # iteration count dominates smoke-test wall time
        out = _run(
            "seq2seq/seq2seq_mp1.py", "--cpu-mesh", "--epoch", "1",
            "--batchsize", "32", "--n-train", "64", "--n-test", "32",
            "--unit", "32", tmp_path=tmp_path, devices=2,
        )
        assert "train/loss" in out

    def test_moe_lm_composed(self, tmp_path):
        out = _run(
            "moe_lm/train_moe_lm.py", "--cpu-mesh", "--sp", "2",
            "--tp", "2", "--steps", "6", "--report-every", "3",
            "--seq-len", "32", "--d-model", "32", "--n-layers", "2",
            "--vocab", "64", "--vocab-parallel", "--generate", "8",
            tmp_path=tmp_path,
        )
        assert "final:" in out
        # the vocab-parallel head samples natively (frontier-row gather)
        assert "sampled (vp+tp/ep-sharded MoE KV-cache decode)" in out

    def test_moe_lm_composed_sampling(self, tmp_path):
        # train sharded (SP x TP x EP), then sample through the
        # tp/ep-sharded KV-cache decode under the same mesh
        out = _run(
            "moe_lm/train_moe_lm.py", "--cpu-mesh", "--sp", "2",
            "--tp", "2", "--steps", "4", "--report-every", "2",
            "--seq-len", "32", "--d-model", "32", "--n-layers", "2",
            "--vocab", "64", "--generate", "8", tmp_path=tmp_path,
        )
        assert "sampled (tp/ep-sharded MoE KV-cache decode)" in out

    def test_lm_sp_tp_train_and_sample(self, tmp_path):
        out = _run(
            "lm/train_lm.py", "--cpu-mesh", "--sp", "2", "--tp", "2",
            "--steps", "6", "--report-every", "3", "--seq-len", "32",
            "--d-model", "32", "--n-layers", "2", "--vocab", "64",
            "--generate", "8", tmp_path=tmp_path,
        )
        assert "final:" in out
        assert "sampled (tp-sharded KV-cache decode)" in out

    def test_lm_serve_mode(self, tmp_path):
        """ISSUE 13 satellite: the --serve mode wires the trained
        checkpoint to the continuous-batching engine (greedy decode
        over the paged KV cache) and reports throughput + token
        latency percentiles."""
        out = _run(
            "lm/train_lm.py", "--cpu-mesh", "--steps", "10",
            "--report-every", "5", "--seq-len", "64", "--d-model", "32",
            "--n-layers", "2", "--vocab", "64", "--generate", "0",
            "--serve", "4", "--serve-tokens", "6",
            "--serve-capacity", "2", tmp_path=tmp_path,
        )
        assert "final:" in out
        assert "served 4 requests" in out
        assert "failed 0" in out

    def test_lm_vocab_parallel_train_and_sample(self, tmp_path):
        """vp tier end-to-end: vp_lm_loss training + native vp decode
        (the embedding/tied head stay sharded through sampling)."""
        out = _run(
            "lm/train_lm.py", "--cpu-mesh", "--tp", "2",
            "--vocab-parallel", "--steps", "6", "--report-every", "3",
            "--seq-len", "32", "--d-model", "32", "--n-layers", "2",
            "--vocab", "64", "--generate", "8", tmp_path=tmp_path,
        )
        assert "final:" in out
        assert "sampled (vocab-parallel KV-cache decode)" in out

    def test_mnist_checkpoint_resume(self, tmp_path):
        args = (
            "mnist/train_mnist_checkpoint.py", "--cpu-mesh",
            "--n-train", "512", "--n-test", "128", "--unit", "64",
        )
        _run(*args, "--epoch", "1", tmp_path=tmp_path)
        out = _run(*args, "--epoch", "2", tmp_path=tmp_path)
        assert "resumed" in out.lower()

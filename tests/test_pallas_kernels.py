"""Pallas kernel tests (interpret mode on CPU).

Pins: flash attention matches the reference attention core (values and
gradients), composes with ring/Ulysses sequence parallelism through the
``attention_fn`` hook, and fused_cast_scale matches cast+multiply.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.ops import multi_head_attention
from chainermn_tpu.ops.pallas_attention import (
    flash_attention,
    flash_attention_fn,
    fused_cast_scale,
)


def _qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d), jnp.float32) * 0.3
    return mk(), mk(), mk()


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv()
        want = multi_head_attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal, None, 16, 16, True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_ragged_lengths_padded_correctly(self):
        # seq length not a multiple of the block: padding keys must not
        # leak into the softmax.
        q, k, v = _qkv(s=23)
        want = multi_head_attention(q, k, v, causal=True)
        got = flash_attention(q, k, v, True, None, 16, 16, True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_cross_attention_lengths(self):
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(2, 16, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 40, 2, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 40, 2, 8), jnp.float32)
        want = multi_head_attention(q, k, v)
        got = flash_attention(q, k, v, False, None, 16, 16, True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_gradients_match_reference(self):
        q, k, v = _qkv(s=16)

        def f_ref(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v, causal=True) ** 2)

        def f_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, True, None, 8, 8, True) ** 2
            )

        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_flash):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=2e-3, atol=2e-4
            )

    def test_split_fwd_bwd_blocks_gradients_exact(self):
        """Separate backward block geometry (round 5: the scoped-VMEM
        limit binds only the backward, so the forward can stream wider
        K/V blocks): value AND gradients with asymmetric fwd/bwd blocks
        must match the shared-block configuration exactly — the block
        decomposition is numerically invisible."""
        q, k, v = _qkv(s=32)

        def f(bq, bk, bwd_bq, bwd_bk):
            def loss(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, True, None, bq, bk, True,
                                    bwd_bq, bwd_bk) ** 2
                )

            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        g_shared = f(8, 8, None, None)
        g_split = f(8, 32, 8, 8)       # wide fwd K blocks, narrow bwd
        g_split2 = f(16, 16, 8, 32)    # and the reverse asymmetry
        for a, b, c in zip(g_shared, g_split, g_split2):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=1e-5, atol=1e-6
            )
            np.testing.assert_allclose(
                np.asarray(c), np.asarray(a), rtol=1e-5, atol=1e-6
            )

    @pytest.mark.parametrize("bq,bk,s_q,s_k", [
        (16, 24, 20, 20),   # blocks don't divide each other, ragged q
        (24, 16, 24, 17),   # ragged k against larger q block
        (8, 32, 40, 40),
    ])
    def test_mismatched_block_sizes(self, bq, bk, s_q, s_k):
        # Regression: q and k/v must be padded by their OWN block sizes;
        # shared padding produced NaN rows or out-of-bounds reads.
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(2, s_q, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, s_k, 2, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, s_k, 2, 8), jnp.float32)
        want = multi_head_attention(q, k, v, causal=(s_q == s_k))
        got = flash_attention(q, k, v, s_q == s_k, None, bq, bk, True)
        assert np.isfinite(np.asarray(got)).all()
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_ragged_lengths(self, causal):
        # seq not a multiple of the block: padded rows/cols must not
        # contribute to dq/dk/dv (the bwd kernels mask by q AND k index)
        q, k, v = _qkv(s=23)

        def f_ref(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v, causal=causal) ** 2)

        def f_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal, None, 16, 16, True) ** 2
            )

        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_flash):
            assert np.isfinite(np.asarray(b)).all()
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=2e-3, atol=2e-4
            )

    def test_gradients_cross_attention(self):
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(2, 16, 2, 8), jnp.float32) * 0.3
        k = jnp.asarray(rng.randn(2, 40, 2, 8), jnp.float32) * 0.3
        v = jnp.asarray(rng.randn(2, 40, 2, 8), jnp.float32) * 0.3

        def f_ref(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v) ** 2)

        def f_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, False, None, 16, 16, True) ** 2
            )

        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_flash):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=2e-3, atol=2e-4
            )

    def test_gradients_bf16(self):
        q, k, v = _qkv(s=32)
        qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))

        def f_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, True, None, 16, 16, True)
                .astype(jnp.float32) ** 2
            )

        g = jax.grad(f_flash, argnums=(0, 1, 2))(qb, kb, vb)

        def f_ref(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v, causal=True) ** 2)

        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g):
            assert b.dtype == jnp.bfloat16
            np.testing.assert_allclose(
                np.asarray(b, np.float32), np.asarray(a),
                rtol=1e-1, atol=5e-2,
            )

    def test_bf16_inputs(self):
        q, k, v = _qkv()
        got = flash_attention(
            q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16), False, None, 16, 16, True,
        )
        want = multi_head_attention(q, k, v)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(want),
            rtol=2e-2, atol=2e-2,
        )


def _brute_census(s_q, s_k, bq, bk, causal, kind):
    """Oracle block classification from the literal padded mask matrix
    (the kernels classify from corner predicates; this classifies every
    element and must agree)."""
    def up(x, m):
        return (x + m - 1) // m * m

    s_qp, s_kp = up(s_q, bq), up(s_k, bk)
    qi = np.arange(s_qp)[:, None]
    kj = np.arange(s_kp)[None, :]
    valid = np.broadcast_to(kj < s_k, (s_qp, s_kp))  # fwd masks only k
    if kind == "bwd":
        valid = valid & (qi < s_q)
    census = {"dead": 0, "interior": 0, "masked": 0,
              "n_q_blocks": s_qp // bq, "n_k_blocks": s_kp // bk}
    for j in range(s_qp // bq):
        for kb in range(s_kp // bk):
            sl = (slice(j * bq, (j + 1) * bq),
                  slice(kb * bk, (kb + 1) * bk))
            c_ok = (kj <= qi)[sl] if causal else np.ones(
                (bq, bk), dtype=bool
            )
            if causal and not c_ok.any():
                census["dead"] += 1
            elif c_ok.all() and valid[sl].all():
                census["interior"] += 1
            else:
                census["masked"] += 1
    return census


class TestDiagonalSplit:
    """The diagonal-split kernel taxonomy: classification correctness,
    bit-exactness vs the pre-split (legacy) kernels, and oracle checks
    at the geometries where the classes meet."""

    @pytest.mark.parametrize("kind", ["fwd", "bwd"])
    @pytest.mark.parametrize("s_q,s_k,bq,bk,causal", [
        (32, 32, 16, 16, True),    # aligned square: all classes present
        (32, 32, 16, 16, False),
        (23, 23, 16, 16, True),    # ragged q AND k tails
        (23, 23, 16, 16, False),
        (48, 48, 8, 16, True),     # bk > bq: coarse diagonal band
        (48, 48, 16, 8, True),     # bq > bk: fully-masked rows exist
        (24, 17, 24, 16, False),   # cross-attention, ragged k
        (40, 40, 8, 32, True),
        (2048, 2048, 1024, 2048, True),   # the shipping fwd geometry
        (8192, 8192, 1024, 1024, True),   # the seq-8192 tier
    ])
    def test_block_census_matches_brute_force(self, kind, s_q, s_k, bq,
                                              bk, causal):
        from chainermn_tpu.ops.pallas_attention import block_census

        assert block_census(s_q, s_k, bq, bk, causal, kind=kind) == \
            _brute_census(s_q, s_k, bq, bk, causal, kind)

    def test_census_shipping_geometries(self):
        """The numbers the perf doc's anatomy section quotes: block
        counts per (batch*head) program at the shipped configs."""
        from chainermn_tpu.ops.pallas_attention import block_census

        # seq 2048, bwd 1024x1024: 1 of 3 live blocks interior
        c = block_census(2048, 2048, 1024, 1024, True, kind="bwd")
        assert c == {"dead": 1, "interior": 1, "masked": 2,
                     "n_q_blocks": 2, "n_k_blocks": 2}
        # seq 2048, fwd 1024x2048 (the r5 split geometry): every live
        # block straddles the diagonal — the split buys the forward
        # nothing at this geometry (the anatomy rungs A/B it against
        # 1024x1024, where 1 of 3 live blocks goes fast-path)
        c = block_census(2048, 2048, 1024, 2048, True)
        assert c["interior"] == 0 and c["masked"] == 2
        # seq 8192, 1024^2: 28 of 36 live blocks interior (78%)
        c = block_census(8192, 8192, 1024, 1024, True)
        assert (c["dead"], c["interior"], c["masked"]) == (28, 28, 8)
        # seq 16384: 120 of 136 live blocks interior (88%)
        c = block_census(16384, 16384, 1024, 1024, True)
        assert (c["dead"], c["interior"], c["masked"]) == (120, 120, 16)
        # non-causal aligned: no mask work anywhere
        c = block_census(64, 64, 16, 16, False)
        assert c["masked"] == 0 and c["interior"] == 16

    def test_census_conservation_and_kind(self):
        from chainermn_tpu.ops.pallas_attention import block_census

        c = block_census(40, 40, 16, 16, True)
        assert c["dead"] + c["interior"] + c["masked"] == \
            c["n_q_blocks"] * c["n_k_blocks"]
        # a ragged q tail reclassifies blocks only for the backward
        fwd = block_census(40, 48, 16, 16, False, kind="fwd")
        bwd = block_census(40, 48, 16, 16, False, kind="bwd")
        assert fwd["masked"] == 0 and bwd["masked"] == 3
        with pytest.raises(ValueError, match="fwd/bwd"):
            block_census(8, 8, 8, 8, False, kind="nope")

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("s,bq,bk", [
        (32, 16, 16),   # block-boundary aligned
        (23, 16, 16),   # ragged tails
        (48, 16, 8),    # fully-masked rows inside live blocks
        (40, 8, 32),    # wide k blocks
    ])
    def test_split_matches_legacy_exactly(self, causal, s, bq, bk):
        """The split kernels must be BIT-IDENTICAL to the pre-split
        kernels in interpret mode, values and all three gradients: the
        interior fast branch skips a mask that is provably all-true,
        and the first-k-block direct write skips a rescale whose factor
        is provably exp(-inf) = 0 — neither may change a single bit."""
        q, k, v = _qkv(s=s, seed=7)

        def run(tax):
            def f(q, k, v):
                return jnp.sum(
                    flash_attention(q, k, v, causal, None, bq, bk, True,
                                    None, None, tax) ** 2
                )

            out = flash_attention(q, k, v, causal, None, bq, bk, True,
                                  None, None, tax)
            grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
            return out, grads

        out_s, g_s = run("split")
        out_l, g_l = run("legacy")
        np.testing.assert_array_equal(np.asarray(out_s), np.asarray(out_l))
        for a, b in zip(g_s, g_l):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_split_matches_legacy_with_lse(self):
        """Same exactness through the (out, lse)-differentiable entry
        point (the ring-attention building block): both outputs and the
        folded g_lse backward."""
        from chainermn_tpu.ops.pallas_attention import (
            flash_attention_with_lse,
        )

        q, k, v = _qkv(s=32, seed=11)

        def run(tax):
            def f(q, k, v):
                out, lse = flash_attention_with_lse(
                    q, k, v, True, None, 16, 16, True, None, None, tax
                )
                return jnp.sum(out ** 2) + jnp.sum(lse * 0.3)

            out, lse = flash_attention_with_lse(
                q, k, v, True, None, 16, 16, True, None, None, tax
            )
            return out, lse, jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        out_s, lse_s, g_s = run("split")
        out_l, lse_l, g_l = run("legacy")
        np.testing.assert_array_equal(np.asarray(out_s), np.asarray(out_l))
        np.testing.assert_array_equal(np.asarray(lse_s), np.asarray(lse_l))
        for a, b in zip(g_s, g_l):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("s", [32, 23])
    def test_split_gradients_match_dense_oracle(self, s):
        """Gradients of the split path vs the dense oracle exactly at
        the geometries where the taxonomy matters: block boundaries
        (s = 2 blocks: the diagonal class) and ragged tails (the tail
        class), with the census proving BOTH live branches executed."""
        from chainermn_tpu.ops.pallas_attention import block_census

        c = block_census(s, s, 16, 16, True, kind="bwd")
        if s == 32:
            assert c["interior"] >= 1 and c["masked"] >= 1
        q, k, v = _qkv(s=s, seed=3)

        def f_ref(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v, causal=True) ** 2)

        def f_split(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, True, None, 16, 16, True, None,
                                None, "split") ** 2
            )

        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        g_split = jax.grad(f_split, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_split):
            assert np.isfinite(np.asarray(b)).all()
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=2e-3, atol=2e-4
            )

    def test_launch_census_applies_clamps(self):
        """launch_census (the bench anatomy rungs' census source) must
        describe the geometry that RUNS: None blocks resolve to the
        defaults, the head-dim clamp and the sequence clamp both
        apply — a clamped launch cannot print the requested census."""
        from chainermn_tpu.ops.pallas_attention import (
            block_census,
            launch_census,
        )

        c = launch_census(2048, 2048, 128)  # defaults at dh=128
        assert c["fwd"] == block_census(2048, 2048, 1024, 1024, True)
        assert c["bwd"] == block_census(2048, 2048, 1024, 1024, True,
                                        kind="bwd")
        # head dim past the measured d<=256 boundary: blocks halve and
        # the census follows the clamp
        c = launch_census(2048, 2048, 512)
        assert c["fwd"] == block_census(2048, 2048, 512, 512, True)
        # split fwd/bwd geometry resolves independently
        c = launch_census(2048, 2048, 128, 1024, 2048, 1024, 1024)
        assert c["fwd"]["n_k_blocks"] == 1 and c["bwd"]["n_k_blocks"] == 2
        # sequence clamp: blocks never exceed the (rounded) sequence
        c = launch_census(64, 64, 128)
        assert c["fwd"]["n_q_blocks"] == 1 and c["fwd"]["n_k_blocks"] == 1
        # compiled TPU floors the q block at the 128 lane tile
        # (_effective_q_block): a sub-128 request must census at 128
        c = launch_census(8192, 8192, 128, 64, 1024)
        assert c["fwd"]["n_q_blocks"] == 8192 // 128
        c = launch_census(8192, 8192, 128, 64, 1024, interpret=True)
        assert c["fwd"]["n_q_blocks"] == 8192 // 64

    def test_interior_taxonomy_timing_only(self):
        """``taxonomy="interior"`` (the anatomy bench's floor) must
        equal split exactly when no mask exists (non-causal aligned),
        and must DIFFER under causal masking — pinning that it is a
        timing knob, not a numerics mode."""
        q, k, v = _qkv(s=32, seed=5)
        args = (None, 16, 16, True, None, None)
        same = flash_attention(q, k, v, False, *args, "interior")
        want = flash_attention(q, k, v, False, *args, "split")
        np.testing.assert_array_equal(np.asarray(same), np.asarray(want))
        wrong = flash_attention(q, k, v, True, *args, "interior")
        right = flash_attention(q, k, v, True, *args, "split")
        assert not np.allclose(np.asarray(wrong), np.asarray(right))

    def test_invalid_taxonomy_raises(self):
        q, k, v = _qkv(s=16)
        with pytest.raises(ValueError, match="taxonomy"):
            flash_attention(q, k, v, True, None, 8, 8, True, None, None,
                            "diagonalize")


class TestFlashWithSequenceParallel:
    def test_ulysses_with_flash_core(self, mesh8):
        from chainermn_tpu.parallel import ulysses_attention

        q, k, v = _qkv(b=2, s=64, h=8, d=8)
        want = multi_head_attention(q, k, v, causal=True)
        core = flash_attention_fn(block_q=8, block_k=8, interpret=True)

        f = jax.jit(
            jax.shard_map(
                lambda q, k, v: ulysses_attention(
                    q, k, v, "mn", causal=True, attention_fn=core
                ),
                mesh=mesh8,
                in_specs=(P(None, "mn"),) * 3,
                out_specs=P(None, "mn"),
                check_vma=False,
            )
        )
        sh = NamedSharding(mesh8, P(None, "mn"))
        got = f(*(jax.device_put(t, sh) for t in (q, k, v)))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )


class TestFusedCastScale:
    @pytest.mark.parametrize("shape", [(7,), (128,), (3, 5, 11), (256, 128)])
    def test_matches_cast_multiply(self, shape):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(*shape), jnp.float32)
        got = fused_cast_scale(x, 0.125, jnp.bfloat16, interpret=True)
        want = (x * 0.125).astype(jnp.bfloat16)
        assert got.shape == x.shape and got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=1e-2,
        )

    def test_empty_input(self):
        x = jnp.zeros((0,), jnp.float32)
        got = fused_cast_scale(x, 0.5, jnp.bfloat16, interpret=True)
        assert got.shape == (0,) and got.dtype == jnp.bfloat16


class TestBlockClamp:
    def test_dim_clamp_table(self):
        """VMEM block clamp (pallas_attention._clamp_blocks_for_dim):
        d <= 256 untouched — the round-5 probe compiled and ran the
        full 1024x1024 geometry at d=192/256 on the real chip, so the
        old d>128 clamp was over-conservative; beyond the measured
        boundary d shrinks by ceil(d/256), floored to lane multiples.
        ``None`` = the 1024 default (the sentinel is what lets the clamp
        distinguish "caller passed nothing" from "caller asked for
        exactly 1024")."""
        import warnings as _w

        from chainermn_tpu.ops.pallas_attention import (
            _clamp_blocks_for_dim,
        )

        with _w.catch_warnings():
            _w.simplefilter("error")  # defaults must clamp SILENTLY
            assert _clamp_blocks_for_dim(None, None, 64) == (1024, 1024)
            assert _clamp_blocks_for_dim(None, None, 128) == (1024, 1024)
            # measured feasible on-chip (round 5): no clamp
            assert _clamp_blocks_for_dim(None, None, 192) == (1024, 1024)
            assert _clamp_blocks_for_dim(None, None, 256) == (1024, 1024)
            # beyond the measured boundary: extrapolated shrink
            assert _clamp_blocks_for_dim(None, None, 512) == (512, 512)
            # floor: never below 256, and always a lane multiple
            bq, bk = _clamp_blocks_for_dim(None, None, 384)
            assert bq >= 256 and bq % 128 == 0
            bq, bk = _clamp_blocks_for_dim(None, None, 1024)
            assert bq >= 256 and bq % 128 == 0

    def test_explicit_blocks_warn_when_clamped(self):
        """Explicitly requested blocks that get shrunk must WARN
        (advisor r4: a tuning sweep at large d would otherwise silently
        measure the clamp, not its requested geometry) — including an
        explicit 1024x1024, which value-equality default detection
        would have missed.  warn=False (the backward's path) and
        unclamped explicit blocks stay silent."""
        import warnings as _w

        from chainermn_tpu.ops import pallas_attention as pa

        pa._warned_geometries.clear()
        with pytest.warns(UserWarning, match="clamped"):
            assert pa._clamp_blocks_for_dim(512, 512, 512) == (256, 256)
        with pytest.warns(UserWarning, match="clamped"):
            assert pa._clamp_blocks_for_dim(1024, 1024, 512) == (512, 512)
        with _w.catch_warnings():
            _w.simplefilter("error")
            # once per geometry: a repeat stays silent
            pa._clamp_blocks_for_dim(512, 512, 512)
            # the backward pass never warns (fwd already did)
            pa._clamp_blocks_for_dim(1024, 512, 512, warn=False)
            # explicit blocks that FIT are silent (incl. the measured
            # d=256 boundary, which rounds 1-4 would have clamped)
            pa._clamp_blocks_for_dim(256, 256, 64)
            pa._clamp_blocks_for_dim(1024, 1024, 256)
        pa._warned_geometries.clear()

    def test_flash_matches_oracle_at_d192(self):
        """The clamp path (d=192: previously unshrunk) must stay
        numerically exact vs the dense oracle."""
        import jax

        from chainermn_tpu.ops import multi_head_attention
        from chainermn_tpu.ops.pallas_attention import flash_attention

        rng = np.random.RandomState(0)
        q, k, v = (
            jnp.asarray(rng.randn(1, 256, 2, 192), jnp.float32)
            for _ in range(3)
        )
        out = flash_attention(q, k, v, causal=True)
        want = multi_head_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5
        )


class TestVmemRetry:
    """ADVICE r5: the d<=256 clamp boundary was measured on v5e only; on
    other TPU generations the default backward geometry may exceed
    scoped VMEM at COMPILE time.  The backward now catches that failure
    and retries with ceil-shrunk blocks (the resilience layer's
    retry-on-failure shape applied to kernel compilation)."""

    def test_retries_with_shrunk_geometry(self, monkeypatch):
        from chainermn_tpu.ops import pallas_attention as pa

        calls = []

        def fake_backward(q, k, v, out, lse, g, causal, scale, bq, bk,
                          interp, taxonomy="split", g_lse=None):
            eff = pa._clamp_blocks_for_dim(bq, bk, q.shape[-1],
                                           warn=False)
            calls.append(eff)
            if eff[0] > 256:
                raise RuntimeError(
                    "Mosaic failed: scoped vmem limit exceeded "
                    f"({eff[0]}x{eff[1]})"
                )
            return "dq", "dk", "dv"

        monkeypatch.setattr(pa, "_flash_backward", fake_backward)
        q = jnp.zeros((1, 8, 1, 64), jnp.float32)
        with pytest.warns(UserWarning, match="scoped VMEM"):
            out = pa._backward_with_vmem_retry(
                q, q, q, q, None, q, False, 1.0, 1024, 1024, False
            )
        assert out == ("dq", "dk", "dv")
        # deterministic halving ladder, floored at the lane tile
        assert calls == [(1024, 1024), (512, 512), (256, 256)]

    def test_non_vmem_failure_propagates(self, monkeypatch):
        from chainermn_tpu.ops import pallas_attention as pa

        def fake_backward(*a, **kw):
            raise RuntimeError("INVALID_ARGUMENT: something else")

        monkeypatch.setattr(pa, "_flash_backward", fake_backward)
        q = jnp.zeros((1, 8, 1, 64), jnp.float32)
        with pytest.raises(RuntimeError, match="something else"):
            pa._backward_with_vmem_retry(
                q, q, q, q, None, q, False, 1.0, 512, 512, False
            )

    def test_exhausted_shrink_reraises(self, monkeypatch):
        from chainermn_tpu.ops import pallas_attention as pa

        def fake_backward(q, k, v, out, lse, g, causal, scale, bq, bk,
                          interp, taxonomy="split", g_lse=None):
            raise RuntimeError("scoped vmem limit exceeded")

        monkeypatch.setattr(pa, "_flash_backward", fake_backward)
        q = jnp.zeros((1, 8, 1, 64), jnp.float32)
        with pytest.warns(UserWarning, match="scoped VMEM"):
            with pytest.raises(RuntimeError, match="vmem"):
                pa._backward_with_vmem_retry(
                    q, q, q, q, None, q, False, 1.0, 256, 256, False
                )

    def test_compile_probe_is_safe_everywhere(self):
        """The AOT compile probe (how VMEM failures are caught on the
        jitted TPU path) must never crash — eagerly or under an outer
        jit trace — and must report not-blocked when the probe itself
        cannot run (CPU backend: non-interpret pallas compile is an
        infrastructure error, not a VMEM verdict)."""
        from chainermn_tpu.ops import pallas_attention as pa

        q = jnp.zeros((1, 128, 1, 64), jnp.float32)
        lse = jnp.zeros((1, 128), jnp.float32)

        assert pa._bwd_compile_blocked(
            (q, q, q, q, lse, q), False, 1.0, 128, 128
        ) is False

        def body(x):
            # probing with tracer-derived shapes during an outer trace
            assert pa._bwd_compile_blocked(
                (x, x, x, x, lse, x), True, 0.5, 128, 128
            ) is False
            return x * 2

        np.testing.assert_allclose(np.asarray(jax.jit(body)(q)), 0.0)

    def test_grad_routes_through_retry(self, monkeypatch):
        """The custom-vjp backward rule must reach the retry wrapper (a
        VMEM failure during jax.grad is recovered, not fatal)."""
        from chainermn_tpu.ops import pallas_attention as pa

        seen = []
        real = pa._flash_backward

        def spying(q, k, v, out, lse, g, causal, scale, bq, bk, interp,
                   taxonomy="split", g_lse=None):
            seen.append((bq, bk))
            if len(seen) == 1:
                raise RuntimeError("scoped vmem limit exceeded")
            return real(q, k, v, out, lse, g, causal, scale, bq, bk,
                        interp, taxonomy=taxonomy, g_lse=g_lse)

        monkeypatch.setattr(pa, "_flash_backward", spying)
        q, k, v = _qkv(s=32)
        with pytest.warns(UserWarning, match="scoped VMEM"):
            g = jax.grad(
                lambda q: jnp.sum(
                    pa.flash_attention(q, k, v, False, None, 256, 256,
                                       True)
                )
            )(q)
        assert len(seen) == 2  # failed once, retried shrunk
        assert seen[1][0] < seen[0][0]
        assert np.isfinite(np.asarray(g)).all()


class TestAnalyticAttnFlops:
    def test_formula(self):
        """bench.py's analytic flash-attention FLOP term (the part XLA
        cannot see): fwd = 4*b*h*s^2*dh, training = 3.5x fwd, causal
        halves — stated in the docstring, pinned here."""
        import bench

        b, h, s, dh, L = 2, 8, 1024, 128, 4
        full = bench._flash_attn_tflops(b, h, s, dh, L, causal=False)
        assert full == pytest.approx(14.0 * b * h * s * s * dh * L / 1e12)
        causal = bench._flash_attn_tflops(b, h, s, dh, L, causal=True)
        assert causal == pytest.approx(full / 2)


class TestTimeKloop:
    def test_measures_and_fallback(self):
        """time_kloop returns a positive per-step time from paired k/2k
        calls, and falls back to the long run's average (never a
        negative paired difference) when timings are noise-dominated."""
        import time as _time

        from chainermn_tpu.utils.benchmarking import time_kloop

        calls = []

        def run_k(n):
            calls.append(n)
            _time.sleep(0.001 * n)
            return np.zeros(1)

        dt, samples = time_kloop(run_k, k=10, repeats=2)
        assert calls[0] == 2  # warm call
        assert dt > 0
        assert len(samples) == 2

        # degenerate timings (instant run_k): fallback stays positive
        dt2, _ = time_kloop(lambda n: np.zeros(1), k=4, repeats=1)
        assert dt2 >= 0

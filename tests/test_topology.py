"""Slice-aware topology: the multi-slice (DCN) grouping model.

Reference parity: ``chainermn/communicators/_communication_utility.py``
(``init_ranks`` hostname grouping) — on TPU the "hostname" is the slice
(``device.slice_index``): chips within a slice are ICI-connected, slices
talk over DCN.  CPU devices expose no ``slice_index``, so these paths
never run in the rest of the suite; here synthetic device objects drive
the slice branch of ``_node_key`` / ``sort_devices`` / ``Topology`` /
``HierarchicalCommunicator._build_mesh`` directly, and a monkeypatched
key function groups REAL virtual CPU devices into fake slices so the
inter-axis collectives actually execute over a slice-derived mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chainermn_tpu.communicators import _topology
from chainermn_tpu.communicators._topology import (
    Topology,
    _node_key,
    sort_devices,
)


class FakeTpuDevice:
    """Minimal stand-in for a PJRT TPU device: slice_index + coords."""

    def __init__(self, dev_id, slice_index, coords=None, process_index=0):
        self.id = dev_id
        self.slice_index = slice_index
        self.coords = coords if coords is not None else (dev_id % 4, 0, 0)
        self.process_index = process_index
        self.platform = "cpu"  # keeps process queries off accelerators

    def __repr__(self):
        return f"FakeTpu(id={self.id}, slice={self.slice_index})"


def _two_slices(chips_per_slice=4):
    return [
        FakeTpuDevice(s * chips_per_slice + c, slice_index=s,
                      coords=(c, 0, 0), process_index=s)
        for s in range(2)
        for c in range(chips_per_slice)
    ]


class TestNodeKey:
    def test_slice_index_preferred(self):
        d = FakeTpuDevice(0, slice_index=3)
        assert _node_key(d) == ("slice", 3)

    def test_process_fallback_without_slice(self):
        # CPU/GPU devices have no slice_index -> group by host process
        cpu = jax.devices("cpu")[0]
        assert _node_key(cpu) == ("process", cpu.process_index)


class TestSortDevices:
    def test_canonical_order_groups_slices_contiguously(self):
        devs = _two_slices()
        scrambled = [devs[i] for i in (5, 0, 7, 2, 6, 1, 4, 3)]
        ordered = sort_devices(scrambled)
        assert [d.id for d in ordered] == list(range(8))
        # slice blocks are contiguous
        assert [d.slice_index for d in ordered] == [0] * 4 + [1] * 4

    def test_coords_break_ties_within_slice(self):
        devs = [
            FakeTpuDevice(10, 0, coords=(1, 0, 0)),
            FakeTpuDevice(11, 0, coords=(0, 0, 0)),
        ]
        ordered = sort_devices(devs)
        assert [d.id for d in ordered] == [11, 10]


class TestTopologyFromSlices:
    def test_two_slices_of_four(self):
        topo = Topology.create(_two_slices())
        assert topo.size == 8
        assert topo.inter_size == 2
        assert topo.intra_sizes == (4,) * 8
        assert topo.inter_ranks == (0,) * 4 + (1,) * 4
        assert topo.intra_ranks == (0, 1, 2, 3) * 2
        assert topo.is_uniform()
        grid = topo.device_grid()
        assert grid.shape == (2, 4)
        assert [d.slice_index for d in grid[0]] == [0] * 4
        assert [d.slice_index for d in grid[1]] == [1] * 4

    def test_ragged_slices_not_uniform(self):
        devs = [FakeTpuDevice(i, slice_index=0) for i in range(3)] + [
            FakeTpuDevice(3 + i, slice_index=1) for i in range(5)
        ]
        topo = Topology.create(devs)
        assert topo.inter_size == 2
        assert not topo.is_uniform()
        with pytest.raises(ValueError, match="same number of chips"):
            topo.device_grid()


class TestFakeSliceGroupingMultiprocess:
    """Fleet-tier regression (surfaced by test_fleet_chaos.py's
    ``test_slice_loss_16_procs_4_slices`` scenario): the multi-process
    CPU backend's degenerate ``slice_index=0`` claim routed every
    gloo world around the ``CHAINERMN_TPU_FAKE_SLICE_SIZE`` grouping —
    the knob only engaged when ``slice_index`` was absent — so exactly
    the worlds whose correlated-slice-loss scenarios need a synthetic
    slice topology could never factorize into it.  The degenerate-claim
    fallback now honors the knob before degrading to per-process
    grouping."""

    def _world(self, n=16):
        # a gloo-CPU fleet world: every device claims slice 0, one
        # device per process — with the backend's REAL id layout
        # (global ids stride 2**17 per process, so any id-based
        # grouping degenerates; the rule must group by canonical
        # position)
        return [
            FakeTpuDevice(i << 17, slice_index=0, coords=(i, 0, 0),
                          process_index=i)
            for i in range(n)
        ]

    def test_fake_slices_group_degenerate_multiprocess_world(
        self, monkeypatch
    ):
        monkeypatch.setenv("CHAINERMN_TPU_FAKE_SLICE_SIZE", "4")
        topo = Topology.create(self._world())
        assert topo.inter_size == 4
        assert set(topo.intra_sizes) == {4}
        # synthetic slice k owns processes [4k, 4(k+1)) — the same
        # grouping FaultSchedule.slice_loss targets
        assert list(topo.inter_ranks) == [r // 4 for r in range(16)]

    def test_without_the_knob_process_grouping_stands(self, monkeypatch):
        monkeypatch.delenv("CHAINERMN_TPU_FAKE_SLICE_SIZE",
                           raising=False)
        topo = Topology.create(self._world())
        assert topo.inter_size == 16
        assert set(topo.intra_sizes) == {1}

    def test_real_slice_layouts_never_regrouped(self, monkeypatch):
        # two REAL slices: the keys differ, the degenerate-claim branch
        # never runs, the knob is ignored
        monkeypatch.setenv("CHAINERMN_TPU_FAKE_SLICE_SIZE", "2")
        topo = Topology.create(_two_slices())
        assert topo.inter_size == 2
        assert set(topo.intra_sizes) == {4}


class TestHierarchicalMeshFromSlices:
    def test_mesh_factorizes_inter_by_intra(self):
        import chainermn_tpu as cmn

        comm = cmn.create_communicator(
            "hierarchical", devices=_two_slices()
        )
        assert dict(comm.mesh.shape) == {"mn_inter": 2, "mn_intra": 4}
        # rank model mirrors the slice grouping
        assert comm.inter_size == 2
        assert comm.intra_size == 4
        # mesh rows == slices: the intra axis (ICI) never crosses a slice
        for row, want_slice in zip(comm.mesh.devices, (0, 1)):
            assert [d.slice_index for d in row] == [want_slice] * 4

    def test_ragged_topology_degrades_loudly_keeping_axis_pair(self):
        """VERDICT r5 weak #3: the ragged fallback used to silently
        drop to a single flat axis — code written against the
        documented ('mn_inter', 'mn_intra') pair then broke, and the
        operator never learned the slice-staged schedule was gone.
        Now: a UserWarning names the ragged sizes, and the axis pair
        survives as a width-1 inter axis."""
        import chainermn_tpu as cmn

        devs = [FakeTpuDevice(i, slice_index=0) for i in range(3)] + [
            FakeTpuDevice(3 + i, slice_index=1) for i in range(5)
        ]
        with pytest.warns(UserWarning, match="ragged topology"):
            comm = cmn.create_communicator("hierarchical", devices=devs)
        assert comm.mesh.axis_names == ("mn_inter", "mn_intra")
        assert comm.mesh.devices.shape == (1, 8)
        # the warning names the offending per-node sizes
        with pytest.warns(UserWarning, match=r"\[3, 5\]"):
            cmn.create_communicator("hierarchical", devices=devs)

    def test_uniform_topology_does_not_warn(self):
        import warnings

        import chainermn_tpu as cmn

        with warnings.catch_warnings():
            warnings.simplefilter("error")
            comm = cmn.create_communicator(
                "hierarchical", devices=_two_slices()
            )
        assert dict(comm.mesh.shape) == {"mn_inter": 2, "mn_intra": 4}

    def test_single_slice_keeps_two_level_layout(self):
        import chainermn_tpu as cmn

        devs = [FakeTpuDevice(i, slice_index=0) for i in range(4)]
        comm = cmn.create_communicator("hierarchical", devices=devs)
        assert dict(comm.mesh.shape) == {"mn_inter": 1, "mn_intra": 4}


@pytest.fixture
def fake_slices(monkeypatch):
    """Group the 8 REAL virtual CPU devices into 2 fake slices of 4 (by
    device id), so slice-derived meshes carry executing collectives."""
    monkeypatch.setattr(
        _topology, "_node_key", lambda d: ("slice", d.id // 4)
    )


class TestSliceGroupedCollectivesExecute:
    """The inter axis built from slice grouping must carry real traffic:
    psum/allgather over a (2, 4) slice-factorized mesh of actual CPU
    devices (the closest a single host gets to multi-slice DCN)."""

    def test_allreduce_over_slice_mesh(self, fake_slices, mesh8):
        import chainermn_tpu as cmn

        comm = cmn.create_communicator(
            "hierarchical", devices=list(mesh8.devices.flat)
        )
        assert dict(comm.mesh.shape) == {"mn_inter": 2, "mn_intra": 4}
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = np.asarray(comm.allreduce(x, op="sum"))
        np.testing.assert_allclose(out, np.full((8, 1), 28.0))

    def test_bcast_data_and_grad_sync_over_slice_mesh(self, fake_slices,
                                                      mesh8):
        import optax

        import chainermn_tpu as cmn

        comm = cmn.create_communicator(
            "hierarchical", devices=list(mesh8.devices.flat)
        )

        def loss_fn(params, batch):
            return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        params = comm.bcast_data({"w": jnp.zeros((4,))})
        step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
        params, opt_state = step.place(params, opt.init(params))
        rows = np.stack(
            [np.full((4,), float(r), np.float32) for r in range(8)]
        )
        params, opt_state, metrics = step(params, opt_state, rows)
        # oracle: w <- w - 0.1 * mean_r(w - r) with mean over global batch
        want = 0.1 * np.mean(np.arange(8))
        np.testing.assert_allclose(
            np.asarray(params["w"]), np.full((4,), want), rtol=1e-6
        )
        assert np.isfinite(float(metrics["loss"]))

    def test_ragged_fallback_executes_flat(self, monkeypatch, mesh8):
        import chainermn_tpu as cmn

        # 3 + 5 chips per "slice": ragged -> degraded mesh (width-1
        # inter axis, loud warning), collectives still correct over
        # REAL devices
        monkeypatch.setattr(
            _topology, "_node_key",
            lambda d: ("slice", 0 if d.id < 3 else 1),
        )
        with pytest.warns(UserWarning, match="ragged topology"):
            comm = cmn.create_communicator(
                "hierarchical", devices=list(mesh8.devices.flat)
            )
        assert comm.mesh.axis_names == ("mn_inter", "mn_intra")
        assert dict(comm.mesh.shape) == {"mn_inter": 1, "mn_intra": 8}
        x = np.ones((8, 2), np.float32)
        out = np.asarray(comm.allreduce(x, op="sum"))
        np.testing.assert_allclose(out, np.full((8, 2), 8.0))

    def test_ragged_fallback_runs_train_step(self, monkeypatch, mesh8):
        """The degraded mesh must still drive the COMPILED tier: the
        axis-pair survival claim is only real if build_train_step's
        sharded program (batch sharding + gradient psum over both axis
        names) compiles and produces correct numerics on it."""
        import optax

        import chainermn_tpu as cmn

        monkeypatch.setattr(
            _topology, "_node_key",
            lambda d: ("slice", 0 if d.id < 3 else 1),
        )
        with pytest.warns(UserWarning, match="ragged topology"):
            comm = cmn.create_communicator(
                "hierarchical", devices=list(mesh8.devices.flat)
            )

        def loss_fn(params, batch):
            return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        params = comm.bcast_data({"w": jnp.zeros((4,))})
        step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
        params, opt_state = step.place(params, opt.init(params))
        rows = np.stack(
            [np.full((4,), float(r), np.float32) for r in range(8)]
        )
        params, opt_state, metrics = step(params, opt_state, rows)
        want = 0.1 * np.mean(np.arange(8))
        np.testing.assert_allclose(
            np.asarray(params["w"]), np.full((4,), want), rtol=1e-6
        )
        assert np.isfinite(float(metrics["loss"]))

"""Serving tier: paged KV cache, continuous-batching decode, elastic
replicas (ISSUE 13).

The acceptance pins:
* paged-cache decode is BIT-IDENTICAL to the dense contiguous-cache
  oracle (0 tolerance, through interleaved joins/leaves and ragged
  final blocks);
* the ``decode_step`` collective budget holds on the compiled
  tensor-parallel program with zero partitioner insertions;
* allocator admit/evict/fragmentation invariants;
* cache state round-trips through the existing checkpoint layer;
* request retry/timeout ride the resilience taxonomy without dropping
  deterministic outputs.
"""

import os
import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import chainermn_tpu as cmn
from chainermn_tpu.models.transformer import TransformerLM, generate
from chainermn_tpu.ops.pallas_attention import (
    flash_decode,
    paged_decode_reference,
)
from chainermn_tpu.serving.batcher import ContinuousBatcher, Request
from chainermn_tpu.serving.decode import DecodeEngine, engine_from_trained
from chainermn_tpu.serving.kv_cache import (
    CacheAdmissionError,
    KVExport,
    NULL_PAGE,
    PagedKVCache,
    PrefixMatch,
    pages_needed,
    reshard_kv_state,
)
from chainermn_tpu.serving.speculative import SpeculativeBatcher
from chainermn_tpu.serving.replica import (
    DecodeReplica,
    RequestJournal,
    claim,
)
from chainermn_tpu.serving.disagg import (
    DisaggDecodeReplica,
    PrefillReplica,
    load_handoff,
    pack_handoff,
    publish_handoff,
    transfer_kv,
    unpack_handoff,
)
from chainermn_tpu.resilience.fault_injection import (
    FaultSpec,
    inject_faults,
)


VOCAB, D, HEADS, LAYERS, MAXLEN = 64, 32, 4, 2, 64


def _cache(capacity=3, page_size=4, pages_per_slot=4, num_pages=None):
    return PagedKVCache(n_layers=LAYERS, n_heads=HEADS,
                        d_head=D // HEADS, capacity=capacity,
                        page_size=page_size,
                        pages_per_slot=pages_per_slot,
                        num_pages=num_pages)


def _shared_prompts(n, seed=17, page=8):
    """Prompts over one page-aligned shared system prefix + unique
    tails — the high-overlap mix prefix sharing exists for."""
    rng = np.random.RandomState(seed)
    head = rng.randint(0, VOCAB, page).tolist()
    return [head + rng.randint(0, VOCAB, 2 + rng.randint(3)).tolist()
            for _ in range(n)]


def _draft_engine(eng, seed=7, zero=False):
    """A half-width 1-layer draft engine built to ``eng``'s exact cache
    geometry (the SpeculativeBatcher contract)."""
    dm = TransformerLM(vocab_size=VOCAB, d_model=16, n_heads=2,
                       n_layers=1, max_len=MAXLEN)
    dp = dm.init(
        {"params": jax.random.PRNGKey(seed),
         "dropout": jax.random.PRNGKey(seed + 1)},
        jnp.zeros((1, 8), jnp.int32),
    )
    if zero:
        dp = jax.tree_util.tree_map(jnp.zeros_like, dp)
    return DecodeEngine(dm, dp, capacity=eng.capacity,
                        page_size=eng.page_size,
                        pages_per_slot=eng.pages_per_slot,
                        num_pages=eng.cache.num_pages)


@pytest.fixture(scope="module")
def lm():
    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=LAYERS, max_len=MAXLEN)
    params = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        jnp.zeros((1, 16), jnp.int32),
    )
    return model, params


@pytest.fixture(scope="module")
def lm_long():
    """A longer-context twin of ``lm`` for the int8 handoff gate: the
    greedy-token-divergence test needs >= 64 generated tokens, which
    MAXLEN=64 cannot hold on top of a prompt."""
    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=LAYERS, max_len=96)
    params = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        jnp.zeros((1, 16), jnp.int32),
    )
    return model, params


def _prompts(seed, n, lo=2, hi=14):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, rng.randint(lo, hi)).tolist()
            for _ in range(n)]


# ----------------------------------------------------------------------
# allocator
# ----------------------------------------------------------------------
class TestAllocator:
    def _cache(self, capacity=3, num_pages=10, page_size=4):
        return PagedKVCache(
            n_layers=1, n_heads=2, d_head=4, capacity=capacity,
            page_size=page_size, num_pages=num_pages, pages_per_slot=4,
        )

    def test_admit_reserves_ceil_pages(self):
        c = self._cache()
        s = c.admit(9)  # ceil(9/4) = 3 pages
        assert len(c._slot_pages[s]) == 3
        assert c.free_pages == 9 - 3
        c.check_invariants()

    def test_null_page_never_allocated(self):
        c = self._cache()
        slots = [c.admit(16) for _ in range(2)]
        for s in slots:
            assert NULL_PAGE not in c._slot_pages[s]
        c.check_invariants()

    def test_admit_is_deterministic(self):
        def run():
            c = self._cache()
            ops = []
            s0 = c.admit(7); ops.append(("a", s0))
            s1 = c.admit(4); ops.append(("a", s1))
            c.release(s0); ops.append(("r", s0))
            s2 = c.admit(12); ops.append(("a", s2))
            return ops, c.block_tables.copy(), list(c._free_pages)

        a, ta, fa = run()
        b, tb, fb = run()
        assert a == b
        np.testing.assert_array_equal(ta, tb)
        assert fa == fb

    def test_no_fragmentation(self):
        """Pages are unit-granularity: after any release pattern, a
        request fits iff the free COUNT suffices — there is no layout
        in which can_admit lies."""
        c = self._cache(capacity=4, num_pages=9, page_size=4)
        slots = [c.admit(8) for _ in range(4)]  # 2 pages each = all 8
        assert not c.can_admit(4)
        c.release(slots[0])
        c.release(slots[2])  # free pages now interleaved with used
        assert c.can_admit(16)  # 4 pages — would span the "holes"
        s = c.admit(16)
        assert len(c._slot_pages[s]) == 4
        c.check_invariants()

    def test_admission_failures_are_loud(self):
        c = self._cache(capacity=1, num_pages=4, page_size=4)
        assert not c.can_admit(100)  # > pages_per_slot
        with pytest.raises(CacheAdmissionError):
            c.admit(100)
        c.admit(4)
        assert not c.can_admit(4)  # no free slot
        with pytest.raises(CacheAdmissionError):
            c.admit(4)

    def test_eviction_victim_is_latest_admitted(self):
        c = self._cache()
        s0 = c.admit(4)
        s1 = c.admit(4)
        assert c.choose_victim() == s1
        c.evict(s1)
        assert c.choose_victim() == s0
        c.check_invariants()

    def test_advance_past_reservation_raises(self):
        c = self._cache()
        s = c.admit(4)  # one page
        c.advance(s, 4)
        with pytest.raises(CacheAdmissionError):
            c.advance(s, 1)

    def test_release_returns_pages_sorted(self):
        c = self._cache()
        s0, s1 = c.admit(8), c.admit(8)
        c.release(s0)
        assert c._free_pages == sorted(c._free_pages)
        c.release(s1)
        assert c.free_pages == c.num_pages - 1
        c.check_invariants()

    def test_op_mix_invariants(self):
        rng = np.random.RandomState(7)
        c = self._cache(capacity=4, num_pages=12, page_size=4)
        live = []
        for _ in range(200):
            if live and rng.rand() < 0.4:
                c.release(live.pop(rng.randint(len(live))))
            else:
                want = int(rng.randint(1, 16))
                if c.can_admit(want):
                    live.append(c.admit(want))
            c.check_invariants()

    def test_pages_needed(self):
        assert pages_needed(1, 4) == 1
        assert pages_needed(4, 4) == 1
        assert pages_needed(5, 4) == 2
        assert pages_needed(0, 4) == 1  # floor: a slot owns >= 1 page


# ----------------------------------------------------------------------
# cache state round-trip + resharding
# ----------------------------------------------------------------------
class TestCacheState:
    def _populated(self):
        c = PagedKVCache(n_layers=2, n_heads=2, d_head=4, capacity=3,
                         page_size=4, pages_per_slot=4)
        rng = np.random.RandomState(0)
        c.k_pages = jnp.asarray(rng.randn(*c.k_pages.shape), c.dtype)
        c.v_pages = jnp.asarray(rng.randn(*c.v_pages.shape), c.dtype)
        s0 = c.admit(10)
        c.admit(5)
        c.advance(s0, 7)
        return c

    def test_state_dict_round_trip_bit_identical(self):
        c = self._populated()
        state = c.state_dict()
        c2 = PagedKVCache(n_layers=2, n_heads=2, d_head=4, capacity=3,
                          page_size=4, pages_per_slot=4)
        c2.load_state_dict(state)
        np.testing.assert_array_equal(
            np.asarray(c.k_pages), np.asarray(c2.k_pages))
        np.testing.assert_array_equal(c.block_tables, c2.block_tables)
        np.testing.assert_array_equal(c.lengths, c2.lengths)
        assert c._free_pages == c2._free_pages
        assert c._slot_pages == c2._slot_pages
        # the restored allocator continues identically
        assert c.can_admit(20) == c2.can_admit(20)
        assert c.admit(6) == c2.admit(6)
        np.testing.assert_array_equal(c.block_tables, c2.block_tables)

    def test_shape_mismatch_rejected(self):
        c = self._populated()
        state = c.state_dict()
        small = PagedKVCache(n_layers=1, n_heads=2, d_head=4,
                             capacity=3, page_size=4, pages_per_slot=4)
        with pytest.raises(ValueError, match="shape mismatch"):
            small.load_state_dict(state)

    def test_dense_oracle_cache_state_round_trips(self, lm):
        """The shape check validates against the CURRENT pool arrays —
        the dense-layout engine replaces them with its contiguous
        per-slot layout, and its own snapshot must round-trip too
        (review regression: the check was hardcoded to the paged
        geometry, so a dense engine rejected its own state_dict)."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           layout="dense")
        slot = eng.admit(8)
        eng.prefill(slot, [1, 2, 3])
        state = eng.cache.state_dict()
        eng2 = DecodeEngine(model, params, capacity=2, page_size=8,
                            layout="dense")
        eng2.cache.load_state_dict(state)
        np.testing.assert_array_equal(
            np.asarray(eng.cache.k_pages), np.asarray(eng2.cache.k_pages))
        np.testing.assert_array_equal(
            eng.cache.lengths, eng2.cache.lengths)

    def test_checkpoint_layer_round_trip(self, tmp_path):
        """The acceptance satellite: cache state rides the EXISTING
        checkpoint layer (save -> resume -> load) bit-identically —
        the replica warm-start path."""
        comm = cmn.create_communicator("single_node")
        ckpt = cmn.create_multi_node_checkpointer(
            "serve", comm, path=str(tmp_path))
        c = self._populated()
        ckpt.save(1, {"kv_cache": c.state_dict()})
        ckpt.wait_until_finished()
        step, restored = ckpt.resume()
        assert step == 1
        c2 = PagedKVCache(n_layers=2, n_heads=2, d_head=4, capacity=3,
                          page_size=4, pages_per_slot=4)
        c2.load_state_dict(restored["kv_cache"])
        np.testing.assert_array_equal(
            np.asarray(c.k_pages), np.asarray(c2.k_pages))
        np.testing.assert_array_equal(
            np.asarray(c.v_pages), np.asarray(c2.v_pages))
        np.testing.assert_array_equal(c.block_tables, c2.block_tables)
        assert c._slot_pages == c2._slot_pages

    def test_reshard_heads_bit_identical_to_fresh_split(self):
        """N->M TP resharding of the page pool == a fresh split of the
        concatenated global cache (heads axis), any N->M."""
        rng = np.random.RandomState(1)
        full_k = rng.randn(2, 5, 4, 8, 4).astype(np.float32)
        full_v = rng.randn(2, 5, 4, 8, 4).astype(np.float32)

        def split(arr, n):
            return [arr[:, :, :, r * 8 // n:(r + 1) * 8 // n]
                    for r in range(n)]

        base = {"block_tables": np.zeros((2, 2), np.int32),
                "lengths": np.zeros((2,), np.int32),
                "active": np.zeros((2,), np.int8),
                "slot_page_counts": np.zeros((2,), np.int32),
                "admit_order": np.zeros((0,), np.int32)}
        for old, new in [(2, 4), (4, 2), (2, 1), (1, 4), (4, 4)]:
            states = [
                dict(base, k_pages=k, v_pages=v)
                for k, v in zip(split(full_k, old), split(full_v, old))
            ]
            out = reshard_kv_state(states, new)
            want_k = split(full_k, new)
            assert len(out) == new
            for got, want in zip(out, want_k):
                np.testing.assert_array_equal(
                    np.asarray(got["k_pages"]), want)

    def test_reshard_rejects_indivisible_heads(self):
        states = [{"k_pages": np.zeros((1, 2, 2, 3, 2)),
                   "v_pages": np.zeros((1, 2, 2, 3, 2))}]
        with pytest.raises(ValueError, match="heads"):
            reshard_kv_state(states, 2)


# ----------------------------------------------------------------------
# KV delta snapshots (ISSUE 19): dirty-page increments for the RAM tier
# ----------------------------------------------------------------------
class TestKVDeltaSnapshot:
    def _replica_pair(self):
        """A populated cache and a replica synced by one full snapshot,
        with agreed delta base markers — the handoff every delta ships
        on top of."""
        c = _cache()
        rng = np.random.RandomState(3)
        c.k_pages = jnp.asarray(rng.randn(*c.k_pages.shape), c.dtype)
        c.v_pages = jnp.asarray(rng.randn(*c.v_pages.shape), c.dtype)
        s0 = c.admit(9)
        c.advance(s0, 8)
        r = _cache()
        r.load_state_dict(c.state_dict())
        r.delta_base_mark(c.delta_base_mark())
        return c, r, s0

    def _assert_synced(self, c, r):
        a, b = c.state_dict(), r.state_dict()
        assert sorted(a) == sorted(b)
        for k in a:
            np.testing.assert_array_equal(
                np.asarray(a[k]), np.asarray(b[k]), err_msg=k
            )

    def test_delta_ships_only_the_dirty_pages(self):
        c, r, _ = self._replica_pair()
        s1 = c.admit(5)
        c.advance(s1, 3)
        touched = set(c._slot_pages[s1])
        d = c.delta_state_dict()
        assert {int(p) for p in d["page_ids"]} == touched
        assert d["k_delta"].shape[1] == len(touched)
        r.apply_delta(d)
        self._assert_synced(c, r)
        # nothing written since the cut: the next delta is empty but
        # still carries the full accounting and verifies
        d2 = c.delta_state_dict()
        assert d2["page_ids"].size == 0
        r.apply_delta(d2)
        self._assert_synced(c, r)

    def test_admit_cow_evict_release_churn_applies_bit_identical(self):
        """The acceptance pin: a delta cut after prefix-shared
        admission, a copy-on-write, an eviction, and a release lands
        the replica bit-identical to loading the sender's FULL
        state_dict — refcounts and CoW reserves included."""
        c, r, s0 = self._replica_pair()
        toks = list(range(8))
        c.register_prefix(s0, toks)
        m = c.lookup_prefix(toks)  # full match → CoW'd final page
        b = c.admit(12, prefix=m)
        assert c.cow_for_write(b, 1) is True
        c.advance(b, 1)
        u = c.admit(5)
        c.advance(u, 5)
        c.evict(u)  # preempt: pages return to the pool
        c.release(s0)  # shared pages survive for b alone
        r.apply_delta(c.delta_state_dict())
        self._assert_synced(c, r)
        # the synced replica's allocator continues identically
        assert c.admit(6) == r.admit(6)
        np.testing.assert_array_equal(c.block_tables, r.block_tables)
        assert c._free_pages == r._free_pages
        c.check_invariants()
        r.check_invariants()

    def test_import_kv_marks_the_imported_pages_dirty(self):
        # the disaggregated handoff writes pages outside admit/advance:
        # those must land in the next delta too
        c, _, s0 = self._replica_pair()
        kv = c.export_kv(s0)
        dst = _cache()
        dst.delta_base_mark()
        slot = dst.import_kv(kv, 12)
        cut = dst.delta_state_dict()
        assert {int(p) for p in cut["page_ids"]} == set(
            dst._slot_pages[slot]
        )

    def test_tampered_delta_rejected_before_any_mutation(self):
        c, r, _ = self._replica_pair()
        s1 = c.admit(5)
        c.advance(s1, 3)
        d = c.delta_state_dict()
        before = r.state_dict()
        evil = dict(d, k_delta=np.asarray(d["k_delta"]) + 1e-3)
        with pytest.raises(ValueError, match="digest mismatch"):
            r.apply_delta(evil)
        # accounting is covered by the digest as well
        evil2 = dict(d, lengths=np.asarray(d["lengths"]) + 1)
        with pytest.raises(ValueError, match="digest mismatch"):
            r.apply_delta(evil2)
        after = r.state_dict()
        for k in before:
            np.testing.assert_array_equal(
                np.asarray(before[k]), np.asarray(after[k]), err_msg=k
            )
        r.apply_delta(d)  # the pristine delta still applies
        self._assert_synced(c, r)

    def test_out_of_order_delta_rejected(self):
        c, r, _ = self._replica_pair()
        s1 = c.admit(5)
        c.advance(s1, 2)
        d1 = c.delta_state_dict()
        c.advance(s1, 1)
        d2 = c.delta_state_dict()
        with pytest.raises(ValueError, match="base marker"):
            r.apply_delta(d2)  # skipped d1
        r.apply_delta(d1)
        r.apply_delta(d2)  # in order: lands
        self._assert_synced(c, r)
        with pytest.raises(ValueError, match="base marker"):
            r.apply_delta(d2)  # replay


# ----------------------------------------------------------------------
# flash_decode kernel (decode-geometry Pallas variant)
# ----------------------------------------------------------------------
class TestFlashDecode:
    def _pages(self, seed=0, B=3, H=4, Dh=32, bs=8, P=12, n=3):
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(B, H, Dh), jnp.float32)
        k = jnp.asarray(rng.randn(P, bs, H, Dh), jnp.float32)
        v = jnp.asarray(rng.randn(P, bs, H, Dh), jnp.float32)
        bt = jnp.asarray([[1, 2, 3], [4, 5, 0], [6, 0, 0]], jnp.int32)
        return q, k, v, bt

    def test_matches_dense_reference_ragged(self):
        q, k, v, bt = self._pages()
        lengths = jnp.asarray([20, 9, 3], jnp.int32)  # ragged tails
        out = flash_decode(q, k, v, bt, lengths, interpret=True)
        ref = paged_decode_reference(q, k, v, bt, lengths)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-6, atol=2e-6)

    def test_single_page_bit_exact(self):
        """One live page = online softmax IS the dense softmax: the
        kernel must match the reference bit for bit."""
        q, k, v, _ = self._pages()
        bt = jnp.asarray([[1], [4], [6]], jnp.int32)
        lengths = jnp.asarray([5, 8, 3], jnp.int32)
        out = flash_decode(q, k, v, bt, lengths, interpret=True)
        ref = paged_decode_reference(q, k, v, bt, lengths)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_zero_length_slot_returns_zeros(self):
        q, k, v, bt = self._pages()
        lengths = jnp.asarray([20, 0, 3], jnp.int32)
        out = flash_decode(q, k, v, bt, lengths, interpret=True)
        assert np.all(np.asarray(out)[1] == 0)
        ref = paged_decode_reference(q, k, v, bt, lengths)
        assert np.all(np.asarray(ref)[1] == 0)

    def test_dead_pages_do_not_contribute(self):
        """Pages past length are skipped entirely: poisoning them (with
        huge finite values) must not change the output."""
        q, k, v, bt = self._pages()
        lengths = jnp.asarray([9, 9, 3], jnp.int32)  # pages 2.. dead
        out = flash_decode(q, k, v, bt, lengths, interpret=True)
        k2 = k.at[3].set(1e9)  # slot 0's 3rd page — dead at length 9
        v2 = v.at[3].set(1e9)
        out2 = flash_decode(q, k2, v2, bt, lengths, interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# ----------------------------------------------------------------------
# decode step: paged vs dense-cache oracle, generate parity
# ----------------------------------------------------------------------
class TestDecodeBitExactness:
    def _script(self, layout, lm):
        """A scripted interleave of joins/leaves with ragged lengths;
        returns every logits row produced, in order."""
        model, params = lm
        rng = np.random.RandomState(1)
        p0 = rng.randint(0, VOCAB, 5).tolist()
        p1 = rng.randint(0, VOCAB, 11).tolist()   # ragged vs page 8
        p2 = rng.randint(0, VOCAB, 3).tolist()
        eng = DecodeEngine(model, params, capacity=3, page_size=8,
                           layout=layout)
        logs = []
        s0 = eng.admit(5 + 12)
        l = eng.prefill(s0, p0); logs.append(l); t0 = int(np.argmax(l))
        for _ in range(2):
            tk = np.zeros(3, np.int32); tk[s0] = t0
            lg = eng.decode_step(tk)
            logs.append(lg[s0].copy()); t0 = int(np.argmax(lg[s0]))
        s1 = eng.admit(11 + 6)
        l = eng.prefill(s1, p1); logs.append(l); t1 = int(np.argmax(l))
        for _ in range(3):
            tk = np.zeros(3, np.int32); tk[s0] = t0; tk[s1] = t1
            lg = eng.decode_step(tk)
            logs.append(lg[[s0, s1]].copy())
            t0, t1 = int(np.argmax(lg[s0])), int(np.argmax(lg[s1]))
        eng.release(s0)  # leave mid-stream; s2 joins into freed pages
        s2 = eng.admit(3 + 4)
        l = eng.prefill(s2, p2); logs.append(l); t2 = int(np.argmax(l))
        for _ in range(2):
            tk = np.zeros(3, np.int32); tk[s1] = t1; tk[s2] = t2
            lg = eng.decode_step(tk)
            logs.append(lg[[s1, s2]].copy())
            t1, t2 = int(np.argmax(lg[s1])), int(np.argmax(lg[s2]))
        return logs

    def test_paged_equals_dense_oracle_bit_identical(self, lm):
        """THE acceptance pin: every logits row of the interleaved
        paged run equals the dense contiguous-cache oracle's at 0
        tolerance — joins, leaves, slot reuse, ragged final blocks."""
        paged = self._script("paged", lm)
        dense = self._script("dense", lm)
        assert len(paged) == len(dense)
        for i, (a, b) in enumerate(zip(paged, dense)):
            np.testing.assert_array_equal(a, b, err_msg=f"row {i}")

    def test_generate_parity_with_transformer_tier(self, lm):
        """Greedy serving decode == transformer.generate's KV-cache
        tier, token for token (trained-checkpoint contract)."""
        model, params = lm
        prompt = [3, 9, 4, 1, 5, 60, 2]
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        got = eng.generate(prompt, 10)
        ref = generate(model, params,
                       jnp.asarray([prompt], jnp.int32), 10)
        assert got == np.asarray(ref)[0].tolist()

    def test_flash_impl_matches_dense_impl(self):
        """The Pallas decode fast path agrees with the dense attend
        (fp32 model so the only delta is the kernel's fp32-vs-compute
        dtype flow and online-softmax association)."""
        model = TransformerLM(vocab_size=VOCAB, d_model=D,
                              n_heads=HEADS, n_layers=LAYERS,
                              max_len=MAXLEN, dtype=jnp.float32)
        params = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)},
            jnp.zeros((1, 16), jnp.int32),
        )
        prompt = [7, 1, 42, 9, 3]
        dense = DecodeEngine(model, params, capacity=2, page_size=8)
        flash = DecodeEngine(model, params, capacity=2, page_size=8,
                             attention_impl="flash")
        s_d = dense.admit(5 + 6); s_f = flash.admit(5 + 6)
        ld = dense.prefill(s_d, prompt)
        lf = flash.prefill(s_f, prompt)  # prefill is dense in both
        np.testing.assert_array_equal(ld, lf)
        t = int(np.argmax(ld))
        for _ in range(4):
            tk = np.zeros(2, np.int32); tk[0] = t
            a = dense.decode_step(tk)[0]
            b = flash.decode_step(tk)[0]
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)
            t = int(np.argmax(a))

    def test_engine_rejects_training_only_shardings(self, lm):
        model, params = lm
        import dataclasses

        sp = dataclasses.replace(model, seq_axis="mn_seq")
        with pytest.raises(ValueError, match="seq_axis=None"):
            DecodeEngine(sp, params)
        eng = engine_from_trained(sp, params, capacity=2, page_size=8)
        assert eng.module.tp_axis is None  # dense twin materialized

    def test_request_over_capacity_rejected(self, lm):
        model, params = lm
        eng = DecodeEngine(model, params, capacity=1, page_size=8,
                           pages_per_slot=2)
        with pytest.raises(ValueError, match="max_total"):
            eng.admit(17)


# ----------------------------------------------------------------------
# tensor-parallel decode: budget pin + shardlint attribution
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def tp_setup(devices8):
    from jax.sharding import PartitionSpec as P
    from chainermn_tpu.parallel import megatron_param_specs, sharded_init

    comm = cmn.create_communicator("mesh", devices=devices8,
                                   sp_size=1, tp_size=2)
    model = TransformerLM(vocab_size=VOCAB, d_model=D, n_heads=HEADS,
                          n_layers=LAYERS, max_len=MAXLEN,
                          tp_axis="mn_model")
    toks = jnp.zeros((4, 16), jnp.int32)
    params, specs = sharded_init(
        lambda t: model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)}, t),
        comm.mesh, (P("mn_data", "mn_seq"),),
        lambda tree: megatron_param_specs(tree, model_axis="mn_model"),
        toks,
    )
    return comm, model, params, specs


class TestTensorParallelDecode:
    def test_decode_step_budget_pin(self, tp_setup):
        """The decode_step ceiling (2 row-parallel psums per layer,
        nothing else) holds EXACTLY on the authored trace of both the
        decode and the prefill program."""
        from chainermn_tpu.analysis import enforce

        comm, model, params, specs = tp_setup
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           comm=comm, param_specs=specs)
        tr = eng.collective_trace("decode")
        census = enforce("decode_step", tr)
        assert census.get("all_reduce") == 2 * LAYERS  # exact, not just <=
        tr_p = eng.collective_trace("prefill", bucket=8)
        assert enforce("decode_step", tr_p).get("all_reduce") == 2 * LAYERS

    def test_prefill_step_budget_pin(self, tp_setup):
        """ISSUE 18: the prefill program gets its OWN pinned name — a
        disaggregated prefill pool runs nothing else all day, so its
        ceiling must not ride along as a decode_step footnote.  Same
        exact 2-row-parallel-psums-per-layer family, zero partitioner
        insertions on the compiled program."""
        from chainermn_tpu.analysis import assert_attributed, enforce

        comm, model, params, specs = tp_setup
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           comm=comm, param_specs=specs)
        tr = eng.collective_trace("prefill", bucket=8)
        census = enforce("prefill_step", tr)
        assert census.get("all_reduce") == 2 * LAYERS  # exact
        rep = assert_attributed(tr, eng.compiled_text("prefill", bucket=8),
                                name="prefill_step")
        assert rep["all_reduce"]["implicit"] == []
        assert rep["all_reduce"]["authored"] == 2 * LAYERS

    def test_decode_step_attributes_with_zero_insertions(self, tp_setup):
        """Shardlint acceptance: every collective in the COMPILED
        decode step is an authored record — the partitioner inserted
        nothing."""
        from chainermn_tpu.analysis import assert_attributed

        comm, model, params, specs = tp_setup
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           comm=comm, param_specs=specs)
        tr = eng.collective_trace("decode")
        rep = assert_attributed(tr, eng.compiled_text("decode"),
                                name="decode_step")
        assert rep["all_reduce"]["implicit"] == []
        assert rep["all_reduce"]["authored"] == 2 * LAYERS
        assert rep["all_reduce"]["lowered"] == 2 * LAYERS

    def test_tp_generate_parity(self, tp_setup):
        """TP paged decode == the transformer TP generate tier."""
        comm, model, params, specs = tp_setup
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           comm=comm, param_specs=specs)
        prompt = [3, 9, 4, 1, 5]
        got = eng.generate(prompt, 8)
        ref = generate(model, params,
                       jnp.asarray([prompt], jnp.int32), 8,
                       comm=comm, param_specs=specs)
        assert got == np.asarray(ref)[0].tolist()

    def test_tp_requires_comm_and_specs(self, tp_setup):
        _comm, model, params, _specs = tp_setup
        with pytest.raises(ValueError, match="mesh"):
            DecodeEngine(model, params, capacity=2)


# ----------------------------------------------------------------------
# continuous batching
# ----------------------------------------------------------------------
class TestContinuousBatcher:
    def test_batched_outputs_equal_single_request_outputs(self, lm):
        """Continuous batching is a SCHEDULING optimization: every
        request's tokens equal an unbatched run's, bit for bit."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=3, page_size=8)
        reqs = [Request(p, 2 + (i % 5))
                for i, p in enumerate(_prompts(11, 7))]
        out = ContinuousBatcher(eng).serve(reqs)
        solo = DecodeEngine(model, params, capacity=1, page_size=8)
        for r in out:
            assert r.state == "done", r
            assert r.output == solo.generate(r.prompt, r.max_new_tokens)

    def test_joins_and_leaves_share_compiled_programs(self, lm):
        """Padded slot model: membership churn across the whole serve
        never retraces — one decode program per capacity, one prefill
        per prompt bucket."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        b = ContinuousBatcher(eng)
        b.serve([Request(p, 3) for p in _prompts(5, 6, lo=2, hi=16)])
        sizes = getattr(eng._fn, "_cache_size", None)
        if callable(sizes):
            buckets = {eng.prompt_bucket(len(p))
                       for p in _prompts(5, 6, lo=2, hi=16)}
            assert eng._fn._cache_size() <= 1 + len(buckets)

    def test_eos_retires_early(self, lm):
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        probe = eng.generate([5, 9, 11], 6)
        eos = probe[4]  # the 2nd generated token
        r = Request([5, 9, 11], 6, eos_id=eos)
        out = ContinuousBatcher(eng).serve([r])[0]
        assert out.state == "done"
        assert out.tokens[-1] == eos
        assert len(out.tokens) == 2

    def test_recoverable_fault_retries_and_outputs_match(self, lm):
        """An injected transient at the decode step re-queues the
        in-flight requests; the retried outputs are bit-identical (the
        request-level slice of the resilience taxonomy)."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        reqs = [Request(p, 4) for p in _prompts(21, 3)]
        from chainermn_tpu.resilience.log import ResilienceLog, attach, detach

        slog = ResilienceLog()
        attach(slog)
        try:
            with inject_faults(
                [FaultSpec("serving.decode_step", "timeout", at=[2])]
            ):
                out = ContinuousBatcher(eng, max_retries=2).serve(reqs)
        finally:
            detach(slog)
        assert slog.counts.get("request_retry", 0) >= 1
        solo = DecodeEngine(model, params, capacity=1, page_size=8)
        for r in out:
            assert r.state == "done"
            assert r.retries >= 0
            assert r.output == solo.generate(r.prompt, r.max_new_tokens)

    def test_retry_budget_exhaustion_fails_request_not_batch(self, lm):
        model, params = lm
        eng = DecodeEngine(model, params, capacity=1, page_size=8)
        reqs = [Request(p, 3) for p in _prompts(31, 2)]
        # every decode step of the FIRST request faults; with
        # max_retries=0 it fails, and the second request (served after)
        # completes untouched by the exhausted spec
        with inject_faults(
            [FaultSpec("serving.decode_step", "timeout", at=[1],
                       max_fires=1)]
        ):
            out = ContinuousBatcher(eng, max_retries=0).serve(reqs)
        states = sorted(r.state for r in out)
        assert states == ["done", "failed"]
        failed = [r for r in out if r.state == "failed"][0]
        assert "retries exhausted" in failed.error

    def test_timeout_fails_overdue_requests(self, lm):
        model, params = lm
        eng = DecodeEngine(model, params, capacity=1, page_size=8)
        b = ContinuousBatcher(eng, timeout_s=0.0)
        r0 = b.submit(Request(_prompts(41, 1)[0], 3))
        import time as _t

        _t.sleep(0.01)
        b.run()
        assert r0.state == "failed" and "timeout" in r0.error

    def test_request_larger_than_pool_rejected_at_submit(self, lm):
        """A request that outsizes the ALLOCATABLE pool (explicit small
        num_pages) can never be admitted: submit() must reject it up
        front — queueing it would spin the serving loop forever with
        zero progress (review regression: only the slot-width bound
        was checked)."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           num_pages=3, pages_per_slot=4)
        assert eng.max_total == 16  # 2 allocatable pages * 8
        b = ContinuousBatcher(eng)
        with pytest.raises(ValueError, match="max_total"):
            b.submit(Request(list(range(20)), 8))

    def test_timeout_rejected_in_multiprocess_world(self):
        """timeout_s reads the rank-LOCAL monotonic clock: two ranks
        straddling the deadline would diverge their admission
        schedules and deadlock the decode psums — a multi-process TP
        world must reject it at construction."""

        class _Comm:
            process_count = 2

        class _Engine:
            comm = _Comm()

        with pytest.raises(ValueError, match="timeout_s"):
            ContinuousBatcher(_Engine(), timeout_s=1.0)

    def test_latency_report_and_spans(self, lm):
        from chainermn_tpu import observability as obs

        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        tel = obs.Telemetry(label="serve-test")
        obs.install(tel)
        try:
            b = ContinuousBatcher(eng)
            b.serve([Request(p, 3) for p in _prompts(51, 3)])
        finally:
            obs.install(None)
        rep = b.latency_report()
        assert rep["done"] == 3 and rep["failed"] == 0
        assert rep["tokens_generated"] == 9
        assert "serving.token_latency" in rep
        assert rep["serving.token_latency"]["n"] > 0
        assert rep["serving.ttft"]["n"] == 3
        names = {s["name"] for s in tel.timeline.spans()}
        assert {"serving.step", "serving.prefill",
                "serving.decode"} <= names

    def test_attribution_joins_decode_trace(self, tp_setup):
        """The latency-attribution hook: attribute() over a serving
        timeline + the engine's decode trace returns the full record
        list (never drops) — the docs/serving.md recipe."""
        from chainermn_tpu import observability as obs

        comm, model, params, specs = tp_setup
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           comm=comm, param_specs=specs)
        tel = obs.Telemetry(label="attr-test")
        obs.install(tel)
        try:
            ContinuousBatcher(eng).serve(
                [Request([1, 2, 3], 2)]
            )
        finally:
            obs.install(None)
        rep = eng.attribution(tel.timeline)
        # compiled-step collectives have no per-collective spans on
        # this path — the report must LIST them as unmatched rather
        # than drop them (attribute()'s never-drop contract)
        total = len(rep.matched) + len(rep.unmatched_records)
        assert total == 2 * LAYERS


# ----------------------------------------------------------------------
# elastic replicas
# ----------------------------------------------------------------------
class TestReplica:
    def test_claim_is_disjoint_complete_and_stable(self):
        docs = [{"id": f"r{i}", "seq": i} for i in range(7)]
        a = claim(docs, 0, 2)
        b = claim(docs, 1, 2)
        assert {d["id"] for d in a} | {d["id"] for d in b} == {
            f"r{i}" for i in range(7)}
        assert not ({d["id"] for d in a} & {d["id"] for d in b})
        # stability: removing served requests does not migrate the rest
        remaining = [d for d in docs if d["id"] not in ("r0", "r2")]
        a2 = claim(remaining, 0, 2)
        assert {d["id"] for d in a2} == {"r4", "r6"}

    def test_journal_seq_ignores_torn_tmp_files(self, tmp_path):
        """seq derives from the COMMITTED request files (max + 1), so
        a crashed submitter's leftover ``.tmp`` can neither skip seqs
        nor shadow one (review regression: counting every ``req_``
        prefix included tmp files)."""
        j = RequestJournal(str(tmp_path))
        j.submit(Request([1], 2, id="a"))
        open(os.path.join(str(tmp_path),
                          "req_000001_ghost.json.tmp999"), "w").close()
        j.submit(Request([2], 2, id="b"))
        assert [(d["id"], d["seq"]) for d in j.requests()] == [
            ("a", 0), ("b", 1)]

    def test_journal_round_trip(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        reqs = [Request([1, 2, 3], 4, id=f"r{i}") for i in range(3)]
        j.submit_all(reqs)
        assert [d["id"] for d in j.requests()] == ["r0", "r1", "r2"]
        assert len(j.pending()) == 3
        reqs[1].tokens = [7, 8]
        reqs[1].state = "done"
        j.write_result(reqs[1])
        assert [d["id"] for d in j.pending()] == ["r0", "r2"]
        assert j.results()["r1"]["tokens"] == [1, 2, 3, 7, 8]

    def test_unservable_journaled_request_fails_loudly(self, lm,
                                                       tmp_path):
        """A journaled request NO engine of this replica's geometry can
        admit must fail in the journal (loud, result written) while the
        rest of the share completes — crashing or wedging the claim
        loop would take every other request down with it."""
        model, params = lm
        j = RequestJournal(str(tmp_path))
        j.submit_all([Request(list(range(20)), 8, id="big"),
                      Request([1, 2, 3], 3, id="ok")])
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           num_pages=3, pages_per_slot=4)
        rep = DecodeReplica(eng, j)
        rep.serve()
        res = j.results()
        assert res["big"]["state"] == "failed"
        assert "max_total" in res["big"]["error"]
        assert res["ok"]["state"] == "done"
        assert len(j.pending()) == 0

    def test_two_replicas_partition_stream(self, lm, tmp_path):
        model, params = lm
        j = RequestJournal(str(tmp_path))
        j.submit_all([Request(p, 3, id=f"r{i}")
                      for i, p in enumerate(_prompts(61, 5))])
        reps = [
            DecodeReplica(
                DecodeEngine(model, params, capacity=2, page_size=8),
                j, replica_index=i, n_replicas=2)
            for i in range(2)
        ]
        s0 = reps[0].serve()
        s1 = reps[1].serve()
        assert sorted(s0) == ["r0", "r2", "r4"]
        assert sorted(s1) == ["r1", "r3"]
        assert len(j.pending()) == 0

    def test_preempt_drains_and_survivor_completes_bit_identical(
            self, lm, tmp_path):
        """The elastic-replica acceptance, single-process tier (the mp
        tier's serving_churn scenario runs it across real processes
        with a hard kill): a preemption notice drains the replica
        mid-stream — queued requests stay journaled — and the
        re-formed world completes them with outputs bit-identical to
        the no-fault run."""
        model, params = lm
        j = RequestJournal(str(tmp_path))
        docs = [Request(p, 3, id=f"q{i}")
                for i, p in enumerate(_prompts(71, 4))]
        j.submit_all(docs)
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        rep = DecodeReplica(eng, j, replica_index=0, n_replicas=1)
        with inject_faults(
            [FaultSpec("serving.decode_step", "preempt", at=[2])]
        ):
            rep.serve()
        assert rep.drained
        assert len(j.pending()) == 4  # nothing dropped
        # no-fault oracle
        oracle_eng = DecodeEngine(model, params, capacity=2, page_size=8)
        oracle = {r.id: oracle_eng.generate(r.prompt, r.max_new_tokens)
                  for r in docs}
        survivor = DecodeReplica(
            DecodeEngine(model, params, capacity=2, page_size=8),
            j, replica_index=0, n_replicas=1)
        survivor.serve()
        assert len(j.pending()) == 0
        res = j.results()
        for rid, want in oracle.items():
            assert res[rid]["tokens"] == want, rid

    def test_warm_start_resumes_in_flight_bit_identical(
            self, lm, tmp_path):
        """The warm-start contract end to end: a preempted replica
        with a checkpointer drains pages AND in-flight request state;
        the rejoining replica adopts those requests — resuming decode
        mid-stream from the restored pages instead of replaying the
        prompt — and completes the whole stream bit-identically to the
        no-fault run (review regression: restored-active slots had no
        owning request, wedging admission forever when the drained
        cache was full)."""
        model, params = lm
        comm = cmn.create_communicator("single_node")
        ckpt = cmn.create_multi_node_checkpointer(
            "warm", comm, path=str(tmp_path / "ck"))
        j = RequestJournal(str(tmp_path / "j"))
        docs = [Request(p, 4, id=f"w{i}")
                for i, p in enumerate(_prompts(81, 3))]
        j.submit_all(docs)
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        rep = DecodeReplica(eng, j, checkpointer=ckpt)
        with inject_faults(
            [FaultSpec("serving.decode_step", "preempt", at=[2])]
        ):
            rep.serve()
        assert rep.drained
        ckpt.wait_until_finished()
        oracle_eng = DecodeEngine(model, params, capacity=2, page_size=8)
        oracle = {r.id: oracle_eng.generate(r.prompt, r.max_new_tokens)
                  for r in docs}
        eng2 = DecodeEngine(model, params, capacity=2, page_size=8)
        rep2 = DecodeReplica(eng2, j, checkpointer=ckpt)
        assert rep2.warm_start() is not None
        # the drained in-flight requests were adopted mid-decode:
        # tokens already generated, slots still occupied, and the
        # timeout deadline restarted (submitted_at set — a None would
        # exempt resumed requests from timeout_s forever)
        assert rep2.batcher.active
        assert all(r.tokens for r in rep2.batcher.active.values())
        assert all(r.submitted_at is not None
                   for r in rep2.batcher.active.values())
        rep2.serve()
        assert len(j.pending()) == 0
        res = j.results()
        for rid, want in oracle.items():
            assert res[rid]["tokens"] == want, rid

    def test_drain_snapshot_warm_start(self, lm, tmp_path):
        """drain() routes the cache through the checkpoint layer;
        warm_start() on a fresh replica restores the pages
        bit-identically — and releases a restored-active slot no
        in-flight request owns (the engine-driven admit here never
        registered with the batcher, so nothing would ever free it;
        keeping it would wedge admission forever)."""
        model, params = lm
        comm = cmn.create_communicator("single_node")
        ckpt = cmn.create_multi_node_checkpointer(
            "replica", comm, path=str(tmp_path / "ck"))
        j = RequestJournal(str(tmp_path / "j"))
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        rep = DecodeReplica(eng, j, checkpointer=ckpt)
        slot = eng.admit(8)
        eng.prefill(slot, [1, 2, 3, 4])
        rep.drain(step=1)
        ckpt.wait_until_finished()
        eng2 = DecodeEngine(model, params, capacity=2, page_size=8)
        rep2 = DecodeReplica(eng2, j, checkpointer=ckpt)
        assert rep2.warm_start() == 1
        np.testing.assert_array_equal(
            np.asarray(eng.cache.k_pages), np.asarray(eng2.cache.k_pages))
        # the orphaned slot was released: full capacity is admittable
        # again and the allocator is consistent
        assert not eng2.cache.active[slot]
        assert eng2.cache.free_pages == eng2.cache.num_pages - 1
        eng2.cache.check_invariants()


# ----------------------------------------------------------------------
# adaptive drain (ISSUE 15): the serving escalation of the
# straggler-adaptive policy
# ----------------------------------------------------------------------
class TestAdaptiveDrain:
    """``drain_replica`` marks the slow replica draining in the
    journal; the deterministic ``seq % n`` claim re-derives around it,
    so the draining replica's share migrates to healthy replicas with
    no coordination — and every request still completes bit-identically
    to a fresh oracle engine (the ISSUE 15 serving acceptance)."""

    def test_claim_reassigns_draining_share_disjoint_complete(self):
        docs = [{"id": f"r{i}", "seq": i} for i in range(12)]
        shares = [claim(docs, k, 3, draining=[1]) for k in range(3)]
        ids = [{d["id"] for d in s} for s in shares]
        # the draining replica claims nothing; the others partition the
        # whole stream disjointly
        assert ids[1] == set()
        assert ids[0] | ids[2] == {f"r{i}" for i in range(12)}
        assert not ids[0] & ids[2]
        # deterministic: the reassignment is a pure function of seq and
        # the draining set, so every replica derives the same partition
        again = [claim(docs, k, 3, draining=[1]) for k in range(3)]
        assert [{d["id"] for d in s} for s in again] == ids
        # base shares of healthy replicas are unchanged (only the
        # draining replica's share moved)
        base0 = {d["id"] for d in claim(docs, 0, 3)}
        assert base0 <= ids[0]

    def test_all_draining_falls_back_to_base_partition(self):
        docs = [{"id": f"r{i}", "seq": i} for i in range(6)]
        shares = [claim(docs, k, 2, draining=[0, 1]) for k in range(2)]
        # a fully draining world must keep serving, not wedge
        assert {d["id"] for d in shares[0]} == {"r0", "r2", "r4"}
        assert {d["id"] for d in shares[1]} == {"r1", "r3", "r5"}

    def test_journal_drain_markers_round_trip(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        assert j.draining() == []
        j.mark_draining(2)
        j.mark_draining(0)
        assert j.draining() == [0, 2]
        j.clear_draining(2)
        assert j.draining() == [0]
        # markers never pollute the request/result scans
        j.submit(Request([1], 2, id="a"))
        assert [d["id"] for d in j.requests()] == ["a"]
        assert j.results() == {}

    def test_drained_replica_share_migrates_bit_identical(
        self, lm, tmp_path
    ):
        """The acceptance path: replica 1 is convicted slow and
        drained; replica 0 completes the WHOLE stream — including the
        migrated share — with outputs bit-identical to a fresh
        single-engine oracle, while the drained replica claims nothing
        new."""
        from chainermn_tpu.resilience.adaptive import drain_replica
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        model, params = lm
        j = RequestJournal(str(tmp_path))
        docs = [Request(p, 3, id=f"d{i}")
                for i, p in enumerate(_prompts(91, 6))]
        j.submit_all(docs)
        slog = ResilienceLog()
        attach(slog)
        try:
            drain_replica(j, 1, reason="convicted straggler")
        finally:
            detach(slog)
        dec = slog.events("adapt_decision")
        assert dec and dec[0].info["action"] == "drain"
        assert dec[0].info["process"] == 1
        assert slog.events("adapt_action", "adaptive.drain")
        # the draining replica serves nothing new
        drained = DecodeReplica(
            DecodeEngine(model, params, capacity=2, page_size=8),
            j, replica_index=1, n_replicas=2)
        assert drained.serve() == {}
        # the healthy replica absorbs the whole stream
        healthy = DecodeReplica(
            DecodeEngine(model, params, capacity=2, page_size=8),
            j, replica_index=0, n_replicas=2)
        healthy.serve()
        assert len(j.pending()) == 0
        oracle_eng = DecodeEngine(model, params, capacity=2,
                                  page_size=8)
        res = j.results()
        for r in docs:
            want = oracle_eng.generate(r.prompt, r.max_new_tokens)
            assert res[r.id]["tokens"] == want, r.id

    def test_cleared_drain_restores_base_claim(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        j.submit_all([Request([1], 1, id=f"c{i}") for i in range(4)])
        j.mark_draining(1)
        assert claim(j.pending(), 1, 2,
                     draining=j.draining()) == []
        j.clear_draining(1)
        share = claim(j.pending(), 1, 2, draining=j.draining())
        assert {d["id"] for d in share} == {"c1", "c3"}


# ----------------------------------------------------------------------
class TestReplicaAutoscaler:
    """ISSUE 16: load-driven replica-pool sizing over the journal's
    drain markers — AdaptPolicy-shaped hysteresis, one decision maker,
    the markers as the broadcast."""

    def _scaler(self, tmp_path, **kw):
        from chainermn_tpu.serving import ReplicaAutoscaler

        j = RequestJournal(str(tmp_path))
        kw.setdefault("scale_after", 2)
        kw.setdefault("cooldown_windows", 1)
        return j, ReplicaAutoscaler(j, 4, **kw)

    def test_validation_is_eager(self, tmp_path):
        from chainermn_tpu.serving import ReplicaAutoscaler

        j = RequestJournal(str(tmp_path))
        with pytest.raises(ValueError, match="pool_size"):
            ReplicaAutoscaler(j, 0)
        with pytest.raises(ValueError, match="min_replicas"):
            ReplicaAutoscaler(j, 2, min_replicas=3)
        with pytest.raises(ValueError, match="scale_after"):
            ReplicaAutoscaler(j, 2, scale_after=0)
        with pytest.raises(ValueError, match="queue_per_replica"):
            ReplicaAutoscaler(j, 2, queue_per_replica=0)

    def test_scale_up_needs_sustained_pressure_then_cools_down(
        self, tmp_path
    ):
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        j, a = self._scaler(tmp_path, queue_per_replica=4)
        j.mark_draining(2)
        j.mark_draining(3)
        assert a.active() == [0, 1]
        slog = ResilienceLog()
        attach(slog)
        try:
            # 2 active * 4/replica = 8 capacity; 20 queued is pressure
            assert a.observe(queue_depth=20) is None  # streak 1
            act = a.observe(queue_depth=20)
            assert act == {"action": "scale_up", "replica": 2,
                           "active": 3, "queue_depth": 20}
            assert a.active() == [0, 1, 2]  # marker lifted
            # cooldown blocks the immediate next window (the streak
            # keeps accumulating under it — AdaptPolicy's shape)
            assert a.observe(queue_depth=20) is None
            act2 = a.observe(queue_depth=20)
            assert act2["action"] == "scale_up" and act2["replica"] == 3
        finally:
            detach(slog)
        decs = slog.events("autoscale_decision")
        assert [e.info["action"] for e in decs] == ["scale_up"] * 2
        assert slog.events("autoscale_action")
        assert a.totals == {"scale_up": 2, "scale_down": 0}
        # pool exhausted: pressure can no longer accumulate a streak
        assert a.observe(queue_depth=99) is None
        assert a.observe(queue_depth=99) is None
        assert a.streaks == {"up": 0, "down": 0}

    def test_scale_down_sheds_highest_active_to_min(self, tmp_path):
        j, a = self._scaler(tmp_path, queue_per_replica=4,
                            min_replicas=2, cooldown_windows=0)
        assert a.active() == [0, 1, 2, 3]
        # queue 4 <= 4 * (4-1): relief
        assert a.observe(queue_depth=4) is None
        act = a.observe(queue_depth=4)
        assert act == {"action": "scale_down", "replica": 3,
                       "active": 3, "queue_depth": 4}
        assert j.draining() == [3]
        a.observe(queue_depth=0)
        act2 = a.observe(queue_depth=0)
        assert act2["action"] == "scale_down" and act2["replica"] == 2
        # at min_replicas the down streak stops accumulating
        assert a.observe(queue_depth=0) is None
        assert a.observe(queue_depth=0) is None
        assert a.active() == [0, 1]

    def test_flapping_load_never_scales(self, tmp_path):
        j, a = self._scaler(tmp_path, queue_per_replica=4,
                            min_replicas=1)
        j.mark_draining(3)
        # pressure / relief alternating: neither streak survives
        for depth in (99, 0, 99, 0, 99, 0):
            assert a.observe(queue_depth=depth) is None
        assert a.totals == {"scale_up": 0, "scale_down": 0}

    def test_p99_latency_is_a_scale_up_signal(self, tmp_path):
        j, a = self._scaler(tmp_path, queue_per_replica=100,
                            p99_high_s=0.5)
        j.mark_draining(3)
        # queue is shallow but the pool is slow: p99 drives the streak
        assert a.observe(queue_depth=1, p99_token_s=2.0) is None
        act = a.observe(queue_depth=1, p99_token_s=2.0)
        assert act["action"] == "scale_up" and act["replica"] == 3
        # hot p99 also vetoes relief
        a2 = self._scaler(tmp_path, queue_per_replica=100,
                          p99_high_s=0.5)[1]
        assert a2.observe(queue_depth=0, p99_token_s=2.0) is None
        assert a2.streaks["down"] == 0

    def test_queue_depth_defaults_to_journal_pending(self, tmp_path):
        j, a = self._scaler(tmp_path, queue_per_replica=1,
                            scale_after=1, cooldown_windows=0)
        j.mark_draining(3)
        j.submit_all([Request([1], 1, id=f"q{i}") for i in range(9)])
        act = a.observe()
        assert act["action"] == "scale_up"
        assert act["queue_depth"] == 9

    def test_standby_pool_mode_serves_after_activation(
        self, lm, tmp_path
    ):
        """End-to-end slice of the autoscale loop in one process: a
        drain-marked standby polls in ``serve(until_complete=...)``
        without exiting; the autoscaler lifts its marker (scale-up)
        mid-poll; the standby re-derives its share and completes the
        stream bit-identically to a fresh oracle engine."""
        import threading

        from chainermn_tpu.serving import ReplicaAutoscaler

        model, params = lm
        j = RequestJournal(str(tmp_path))
        reqs = [Request(p, 3, id=f"s{i}")
                for i, p in enumerate(_prompts(17, 4))]
        j.submit_all(reqs)
        j.mark_draining(0)  # pool of 1, standby
        rep = DecodeReplica(
            DecodeEngine(model, params, capacity=2, page_size=8),
            j, replica_index=0, n_replicas=1)
        out = {}

        def _serve():
            out["served"] = rep.serve(until_complete=len(reqs),
                                      timeout_s=30.0)

        t = threading.Thread(target=_serve)
        t.start()
        a = ReplicaAutoscaler(j, 1, scale_after=1, cooldown_windows=0,
                              queue_per_replica=1)
        assert a.observe()["action"] == "scale_up"  # queue 4 > 1*1
        t.join(timeout=60)
        assert not t.is_alive()
        assert len(out["served"]) == len(reqs)
        res = j.results()
        oracle = DecodeEngine(model, params, capacity=2, page_size=8)
        for r in reqs:
            want = oracle.generate(r.prompt, r.max_new_tokens)
            assert res[r.id]["tokens"] == want, r.id
        assert j.pending() == []


# ----------------------------------------------------------------------
# prefix-sharing KV cache (ISSUE 17)
# ----------------------------------------------------------------------
class TestPrefixSharing:
    def test_alias_admission_shares_pages(self):
        """A page-aligned prompt prefix registered by one slot admits a
        second slot ALIASING those pages — refcount 2, lengths start at
        the shared length, one fresh tail page only."""
        c = _cache()
        toks = list(range(8))  # two full pages at page_size 4
        a = c.admit(9)
        c.advance(a, 8)  # prompt prefilled
        assert c.register_prefix(a, toks) == 2  # prefix-closed chains
        m = c.lookup_prefix(toks + [9, 10])
        assert m == PrefixMatch(tuple(c._slot_pages[a][:2]), 8, False)
        used0 = c.used_pages
        b = c.admit(11, prefix=m)
        assert int(c.lengths[b]) == 8  # only the tail prefills
        assert c._slot_pages[b][:2] == c._slot_pages[a][:2]
        assert c.used_pages == used0 + 1  # one fresh page, not three
        assert all(int(c._refcounts[p]) == 2 for p in m.pages)
        c.check_invariants()

    def test_fully_matched_prompt_caps_and_copies_on_write(self):
        """An identical resubmitted prompt matches ALL its pages; the
        shared length caps one short (the tail prefill needs a token),
        which marks the final page copy-on-write: the reserve earmarked
        at admission absorbs the write and the original page — still
        read by the registrant — is never touched."""
        c = _cache()
        toks = list(range(8))
        a = c.admit(8)
        c.advance(a, 8)
        c.register_prefix(a, toks)
        m = c.lookup_prefix(toks)
        assert m.shared_len == 7 and m.cow
        b = c.admit(12, prefix=m)
        assert b in c._cow_reserve
        c.check_invariants()
        shared_last = c._slot_pages[b][1]
        assert shared_last == c._slot_pages[a][1]
        # position 7 lands in the still-shared page: the copy happens
        assert c.cow_for_write(b, 1) is True
        assert c._slot_pages[b][1] != shared_last
        assert int(c._refcounts[shared_last]) == 1  # a's again, alone
        c.advance(b, 1)
        c.check_invariants()
        # now private: no further copies on this slot
        assert c.cow_for_write(b, 1) is False

    def test_advance_into_shared_page_without_cow_trips(self):
        """The tripwire behind the bit-identity guarantee: accounting a
        write into a refcount>1 page without ``cow_for_write`` raises
        instead of corrupting another request's history."""
        c = _cache()
        toks = list(range(8))
        a = c.admit(8)
        c.advance(a, 8)
        c.register_prefix(a, toks)
        b = c.admit(12, prefix=c.lookup_prefix(toks))
        with pytest.raises(CacheAdmissionError, match="copy-on-write"):
            c.advance(b, 1)

    def test_release_frees_only_at_refcount_zero(self):
        """Shared pages survive their registrant's release (the alias
        still reads them) and return to the pool — with their index
        entries dropped — only when the LAST reader releases."""
        c = _cache()
        toks = list(range(8))
        a = c.admit(9)
        c.advance(a, 8)
        c.register_prefix(a, toks)
        b = c.admit(10, prefix=c.lookup_prefix(toks + [3]))
        shared = set(c._slot_pages[b][:2])
        c.release(a)
        assert all(int(c._refcounts[p]) == 1 for p in shared)
        assert not shared & set(c._free_pages)
        assert c.lookup_prefix(toks + [5]) is not None  # content live
        c.check_invariants()
        c.release(b)
        assert c.used_pages == 0
        assert c.lookup_prefix(toks + [5]) is None  # entries dropped
        c.check_invariants()

    def test_victim_never_holds_a_shared_page(self):
        """choose_victim is LIFO over UNSHARED slots only: with every
        active slot holding a refcount>1 page there is no victim (the
        batcher queues); an unshared slot is picked even when a shared
        one was admitted later."""
        c = _cache(capacity=3)
        toks = list(range(8))
        u = c.admit(5)  # private, admitted first
        c.advance(u, 5)
        a = c.admit(9)
        c.advance(a, 8)
        c.register_prefix(a, toks)
        b = c.admit(10, prefix=c.lookup_prefix(toks + [1]))
        # b is newest but aliases a's pages; a shares them too — only
        # u is evictable despite being oldest
        assert c.choose_victim() == u
        c.check_invariants()
        c.evict(u)
        assert c.choose_victim() is None  # all-shared: nobody evicts
        c.check_invariants()
        c.release(b)
        assert c.choose_victim() == a  # a's pages are private again

    def test_refcount_invariants_under_op_mix(self):
        """Churn: admit (aliased and cold), tail prefill with CoW,
        decode writes, release, evict — ``check_invariants`` (refcount
        == table multiplicity, conservation, victim-never-shared, index
        liveness) holds after EVERY op, and the drained pool is empty."""
        c = _cache(capacity=4, num_pages=24)
        rng = np.random.RandomState(3)
        base = [list(range(8)), list(range(40, 48))]
        live = set()
        for _ in range(160):
            op = rng.randint(3)
            if op == 0 and len(live) < c.capacity:
                prompt = (base[rng.randint(2)]
                          + rng.randint(0, VOCAB,
                                        1 + rng.randint(3)).tolist())
                total = len(prompt) + 4
                m = c.lookup_prefix(prompt)
                if c.can_admit(total, prefix=m):
                    s = c.admit(total, prefix=m)
                    start = int(c.lengths[s])
                    c.cow_for_write(s, len(prompt) - start)
                    c.advance(s, len(prompt) - start)
                    c.register_prefix(s, prompt)
                    live.add(s)
            elif op == 1 and live:
                s = sorted(live)[rng.randint(len(live))]
                room = (len(c._slot_pages[s]) * c.page_size
                        - int(c.lengths[s]))
                if room > 0:
                    c.cow_for_write(s, 1)
                    c.advance(s, 1)
            elif op == 2 and live:
                if rng.randint(2):
                    s = sorted(live)[rng.randint(len(live))]
                    c.release(s)
                    live.discard(s)
                else:
                    v = c.choose_victim()
                    if v is not None:
                        c.evict(v)
                        live.discard(v)
            c.check_invariants()
        for s in sorted(live):
            c.release(s)
        assert c.used_pages == 0
        c.check_invariants()

    def test_shared_serve_bit_identical_with_fewer_pages(self, lm):
        """The tentpole acceptance: a high-overlap serve with sharing
        ON is bit-identical to the sharing-OFF serve AND to the
        unbatched oracle, while the peak DISTINCT page count drops."""
        model, params = lm
        prompts = _shared_prompts(6)

        def serve(share):
            eng = DecodeEngine(model, params, capacity=3, page_size=8)
            b = ContinuousBatcher(eng, share_prefixes=share)
            for i, p in enumerate(prompts):
                b.submit(Request(p, 3 + i % 3, id=f"r{i}"))
            peak = 0
            while b.step():
                peak = max(peak, eng.cache.used_pages)
                eng.cache.check_invariants()
            return b, peak

        hot, peak_hot = serve(True)
        cold, peak_cold = serve(False)
        assert hot.prefix_hits >= 1 and cold.prefix_hits == 0
        assert hot.prefix_tokens_shared >= 8
        assert peak_hot < peak_cold
        solo = DecodeEngine(model, params, capacity=1, page_size=8)
        for rid in hot.finished:
            r1, r0 = hot.finished[rid], cold.finished[rid]
            assert r1.state == "done"
            assert r1.output == r0.output
            assert r1.output == solo.generate(r1.prompt,
                                              r1.max_new_tokens)

    def test_checkpoint_round_trip_with_live_shared_pages(self):
        """state_dict/load_state_dict carry refcounts and the CoW
        reserve: a snapshot taken mid-share reloads with identical
        allocator state, a tampered refcount row refuses to load, and
        a legacy snapshot (no sharing keys) still loads with refcounts
        derived from table multiplicity."""
        c = _cache()
        toks = list(range(8))
        a = c.admit(9)
        c.advance(a, 8)
        c.register_prefix(a, toks)
        c.admit(8, prefix=c.lookup_prefix(toks))  # capped: live reserve
        sd = c.state_dict()
        c2 = _cache()
        c2.load_state_dict(sd)  # runs check_invariants itself
        np.testing.assert_array_equal(c2._refcounts, c._refcounts)
        assert c2._cow_reserve == c._cow_reserve
        np.testing.assert_array_equal(c2.block_tables, c.block_tables)
        assert c2.used_pages == c.used_pages
        bad = dict(sd)
        bad["page_refcounts"] = np.roll(sd["page_refcounts"], 1)
        with pytest.raises(ValueError, match="refcounts"):
            _cache().load_state_dict(bad)
        legacy = {k: v for k, v in sd.items()
                  if k not in ("page_refcounts", "cow_reserve")}
        c3 = _cache()
        c3.load_state_dict(legacy)
        # tables alone reconstruct the sharing (the reserve earmark is
        # a new-format refinement a legacy snapshot never carried)
        owned = {p for pages in c3._slot_pages.values() for p in pages}
        for p in owned:
            assert c3._refcounts[p] == c._refcounts[p]

    def test_reshard_kv_state_preserves_sharing(self):
        """reshard_kv_state re-cuts heads only: the host allocator state
        — refcounts and CoW reserves included — rides through a 2→1
        reshard and the merged cache passes invariants with the same
        sharing structure."""
        c = PagedKVCache(n_layers=LAYERS, n_heads=2, d_head=4,
                         capacity=2, page_size=4, pages_per_slot=4)
        toks = list(range(8))
        a = c.admit(9)
        c.advance(a, 8)
        c.register_prefix(a, toks)
        c.admit(8, prefix=c.lookup_prefix(toks))
        sd = c.state_dict()
        merged = reshard_kv_state([sd, sd], 1)
        big = PagedKVCache(n_layers=LAYERS, n_heads=4, d_head=4,
                           capacity=2, page_size=4, pages_per_slot=4)
        big.load_state_dict(merged[0])
        np.testing.assert_array_equal(big._refcounts, c._refcounts)
        assert big._cow_reserve == c._cow_reserve
        np.testing.assert_array_equal(big.block_tables, c.block_tables)

    def test_warm_start_re_registers_shared_prefixes(self, lm, tmp_path):
        """Journal replica warm start with shared prefixes: a replica
        preempted mid-share drains pages + refcounts; the rejoining
        replica adopts the in-flight requests, RE-REGISTERS their
        prompts (the index itself never snapshots), and the still-
        pending requests alias the restored pages — completing the
        stream bit-identically to a no-fault oracle."""
        model, params = lm
        comm = cmn.create_communicator("single_node")
        ckpt = cmn.create_multi_node_checkpointer(
            "share", comm, path=str(tmp_path / "ck"))
        j = RequestJournal(str(tmp_path / "j"))
        docs = [Request(p, 4, id=f"s{i}")
                for i, p in enumerate(_shared_prompts(4, seed=23))]
        j.submit_all(docs)
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        rep = DecodeReplica(eng, j, checkpointer=ckpt)
        assert rep.batcher.share_prefixes
        with inject_faults(
            [FaultSpec("serving.decode_step", "preempt", at=[2])]
        ):
            rep.serve()
        assert rep.drained
        ckpt.wait_until_finished()
        oracle_eng = DecodeEngine(model, params, capacity=2, page_size=8)
        oracle = {r.id: oracle_eng.generate(r.prompt, r.max_new_tokens)
                  for r in docs}
        eng2 = DecodeEngine(model, params, capacity=2, page_size=8)
        rep2 = DecodeReplica(eng2, j, checkpointer=ckpt)
        assert rep2.warm_start() is not None
        # adopted prompts re-indexed over the restored pages
        assert rep2.batcher.active
        assert eng2.cache._prefix_index
        eng2.cache.check_invariants()
        rep2.serve()
        # the pending claims aliased the restored pages
        assert rep2.batcher.prefix_hits >= 1
        res = j.results()
        for rid, want in oracle.items():
            assert res[rid]["tokens"] == want, rid


# ----------------------------------------------------------------------
# speculative decode (ISSUE 17)
# ----------------------------------------------------------------------
class TestSpeculative:
    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_spec_serve_bit_identical(self, k, lm):
        """Greedy-exact acceptance makes the speculative transcript the
        plain transcript BY CONSTRUCTION: every committed token is a
        target argmax, so outputs equal the unbatched oracle at any k
        (k=1 is the degenerate plain-decode control)."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        b = SpeculativeBatcher(eng, _draft_engine(eng), k=k)
        out = b.serve([Request(p, 2 + i % 4)
                       for i, p in enumerate(_prompts(61, 5))])
        assert b.verify_steps > 0
        solo = DecodeEngine(model, params, capacity=1, page_size=8)
        for r in out:
            assert r.state == "done", r
            assert r.output == solo.generate(r.prompt, r.max_new_tokens)
        # both allocators drained clean and in lockstep
        for cache in (eng.cache, b.draft.cache):
            assert cache.used_pages == 0
            cache.check_invariants()

    def test_all_accepted_when_draft_equals_target(self, lm):
        """A draft that IS the target proposes exactly the target's
        argmax chain: every verifiable proposal accepted (rate 1.0) and
        the outputs still bit-identical."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        draft = DecodeEngine(model, params, capacity=2, page_size=8)
        b = SpeculativeBatcher(eng, draft, k=4)
        out = b.serve([Request(p, 6) for p in _prompts(62, 3)])
        assert b.tokens_proposed > 0
        assert b.acceptance_rate == 1.0
        solo = DecodeEngine(model, params, capacity=1, page_size=8)
        for r in out:
            assert r.output == solo.generate(r.prompt, r.max_new_tokens)

    def test_all_rejected_zero_params_draft(self, lm):
        """The other extreme: a zeroed draft proposes a constant token
        the target (nearly) never emits — every verify step commits via
        the all-rejected path (one corrected token) and the outputs are
        STILL bit-identical; only the acceptance rate collapses."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        b = SpeculativeBatcher(eng, _draft_engine(eng, zero=True), k=4)
        out = b.serve([Request(p, 5) for p in _prompts(63, 3)])
        assert b.tokens_proposed > 0
        assert b.acceptance_rate < 0.5
        solo = DecodeEngine(model, params, capacity=1, page_size=8)
        for r in out:
            assert r.state == "done"
            assert r.output == solo.generate(r.prompt, r.max_new_tokens)

    def test_eos_retires_inside_a_speculative_commit(self, lm):
        """An eos landing mid-commit truncates exactly where plain
        decode stops — speculative over-proposal never leaks tokens
        past the stop."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        probe = eng.generate([5, 9, 11], 6)
        eos = probe[4]  # the 2nd generated token
        eng2 = DecodeEngine(model, params, capacity=2, page_size=8)
        b = SpeculativeBatcher(eng2, _draft_engine(eng2), k=4)
        out = b.serve([Request([5, 9, 11], 6, eos_id=eos)])[0]
        assert out.state == "done"
        assert out.tokens[-1] == eos
        assert len(out.tokens) == 2

    def test_rollback_rewinds_lengths_only(self):
        c = _cache()
        s = c.admit(12)
        c.advance(s, 8)
        pages = list(c._slot_pages[s])
        c.rollback(s, 5)
        assert int(c.lengths[s]) == 5
        assert c._slot_pages[s] == pages  # reservation untouched
        c.advance(s, 3)  # stale positions simply overwritten
        c.check_invariants()
        with pytest.raises(ValueError, match="rollback"):
            c.rollback(s, 9)
        with pytest.raises(ValueError, match="rollback"):
            c.rollback(s, -1)

    def test_construction_validates_geometry_and_layout(self, lm):
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        with pytest.raises(ValueError, match="k must be"):
            SpeculativeBatcher(eng, _draft_engine(eng), k=0)
        dm = TransformerLM(vocab_size=VOCAB, d_model=16, n_heads=2,
                           n_layers=1, max_len=MAXLEN)
        dp = dm.init(
            {"params": jax.random.PRNGKey(7),
             "dropout": jax.random.PRNGKey(8)},
            jnp.zeros((1, 8), jnp.int32),
        )
        mismatched = DecodeEngine(dm, dp, capacity=2, page_size=4)
        with pytest.raises(ValueError, match="geometry"):
            SpeculativeBatcher(eng, mismatched, k=2)
        dense = DecodeEngine(model, params, capacity=2, page_size=8,
                             layout="dense")
        with pytest.raises(ValueError, match="paged"):
            SpeculativeBatcher(dense, _draft_engine(eng), k=2)

    def test_spec_verify_budget_pin(self, tp_setup):
        """The spec_verify_step ceiling: the k-row verify program runs
        the SAME 2 row-parallel psums per layer as single-token decode
        (the amortization that makes speculation pay on a latency-bound
        interconnect) — exact on the authored trace, zero partitioner
        insertions on the compiled program."""
        from chainermn_tpu.analysis import assert_attributed, enforce

        comm, model, params, specs = tp_setup
        eng = DecodeEngine(model, params, capacity=2, page_size=8,
                           comm=comm, param_specs=specs)
        tr = eng.collective_trace("verify", bucket=4)
        census = enforce("spec_verify_step", tr)
        assert census.get("all_reduce") == 2 * LAYERS  # exact
        rep = assert_attributed(tr, eng.compiled_text("verify", bucket=4),
                                name="spec_verify_step")
        assert rep["all_reduce"]["implicit"] == []
        assert rep["all_reduce"]["authored"] == 2 * LAYERS

    def test_warm_start_mirrors_draft_slots(self, lm, tmp_path):
        """A speculative replica preempted mid-burst drains its TARGET
        cache; the rejoining replica warm-starts it and
        ``mirror_adopted`` re-admits every adopted slot into the draft
        at the SAME slot id, re-prefilled to length lockstep — the
        resumed serve completes bit-identically to a plain oracle."""
        model, params = lm
        comm = cmn.create_communicator("single_node")
        ckpt = cmn.create_multi_node_checkpointer(
            "spec", comm, path=str(tmp_path / "ck"))
        j = RequestJournal(str(tmp_path / "j"))
        docs = [Request(p, 4, id=f"v{i}")
                for i, p in enumerate(_prompts(91, 4))]
        j.submit_all(docs)
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        spec = SpeculativeBatcher(eng, _draft_engine(eng), k=2)
        rep = DecodeReplica(eng, j, checkpointer=ckpt, batcher=spec)
        with inject_faults(
            [FaultSpec("serving.spec_verify", "preempt", at=[2])]
        ):
            rep.serve()
        assert rep.drained
        ckpt.wait_until_finished()
        oracle_eng = DecodeEngine(model, params, capacity=2, page_size=8)
        oracle = {r.id: oracle_eng.generate(r.prompt, r.max_new_tokens)
                  for r in docs}
        eng2 = DecodeEngine(model, params, capacity=2, page_size=8)
        spec2 = SpeculativeBatcher(eng2, _draft_engine(eng2), k=2)
        rep2 = DecodeReplica(eng2, j, checkpointer=ckpt, batcher=spec2)
        assert rep2.warm_start() is not None
        assert spec2.active  # adopted mid-flight
        for s in spec2.active:
            assert spec2.draft.cache.active[s]
            assert (int(spec2.draft.cache.lengths[s])
                    == int(eng2.cache.lengths[s]))  # lockstep restored
        rep2.serve()
        res = j.results()
        for rid, want in oracle.items():
            assert res[rid]["tokens"] == want, rid

    def test_batcher_injection_requires_same_engine(self, lm):
        model, params = lm
        eng = DecodeEngine(model, params, capacity=2, page_size=8)
        other = DecodeEngine(model, params, capacity=2, page_size=8)
        b = SpeculativeBatcher(other, _draft_engine(other), k=2)
        with pytest.raises(ValueError, match="engine"):
            DecodeReplica(eng, RequestJournal(tempfile.mkdtemp()),
                          batcher=b)


# ----------------------------------------------------------------------
# disaggregated prefill/decode: role pools + codec-streamed KV handoff
# ----------------------------------------------------------------------
def _bits(x):
    """Raw bytes of an array for 0-tolerance comparison (bf16 pages
    compare as bits, not floats — NaN payloads and signed zeros count)."""
    return np.ascontiguousarray(np.asarray(x)).view(np.uint8)


class TestDisaggregation:
    """ISSUE 18 acceptance: prefill-pool export -> codec wire ->
    decode-pool import is BIT-IDENTICAL to local prefill for the
    lossless codecs (cache dtype bf16, so ``none``/``bf16`` round-trip
    exactly), atomically published through the journal, and
    recoverable past a dead prefill replica (pool-scoped drains,
    orphan re-prefill)."""

    @pytest.mark.parametrize("codec", ["none", "bf16"])
    def test_handoff_bit_identical_to_local_prefill(self, codec, lm):
        """Export -> pack(codec) -> unpack -> import: the imported
        pages equal the exporter's at 0 tolerance, and decoding from
        them equals the unified single-engine serve token for token."""
        model, params = lm
        prompt = _prompts(33, 1, lo=9, hi=14)[0]
        max_new = 6
        pe = DecodeEngine(model, params, capacity=2, page_size=8)
        slot = pe.admit(pe.prompt_bucket(len(prompt)))
        logits = pe.prefill(slot, prompt)
        kv = pe.export_kv(slot)
        kv2, first = transfer_kv(kv, int(np.argmax(logits)), codec)
        de = DecodeEngine(model, params, capacity=2, page_size=8)
        b = ContinuousBatcher(de)
        r = Request(prompt, max_new, id="h")
        b.ingest(r, kv2, first)
        exp = list(pe.cache._slot_pages[slot])
        imp = list(de.cache._slot_pages[r.slot])[:len(exp)]
        np.testing.assert_array_equal(
            _bits(de.cache.k_pages[:, imp]),
            _bits(pe.cache.k_pages[:, exp]))
        np.testing.assert_array_equal(
            _bits(de.cache.v_pages[:, imp]),
            _bits(pe.cache.v_pages[:, exp]))
        b.run()
        oracle = DecodeEngine(model, params, capacity=1,
                              page_size=8).generate(prompt, max_new)
        assert b.finished["h"].output == oracle

    def test_int8_handoff_gated_by_greedy_agreement(self, lm_long):
        """The int8 codec is transfer-once (no next step for an
        error-feedback residual to ride), so its gate is MEASURED
        greedy-token agreement over >= 64 generated tokens against the
        unified oracle — an accuracy question, never a loss pin."""
        model, params = lm_long
        rng = np.random.RandomState(9)
        prompt = rng.randint(0, VOCAB, 12).tolist()
        max_new = 64
        pe = DecodeEngine(model, params, capacity=1, page_size=8)
        slot = pe.admit(pe.prompt_bucket(len(prompt)))
        logits = pe.prefill(slot, prompt)
        kv = pe.export_kv(slot)
        kv2, first = transfer_kv(kv, int(np.argmax(logits)), "int8")
        de = DecodeEngine(model, params, capacity=1, page_size=8)
        b = ContinuousBatcher(de)
        r = Request(prompt, max_new, id="q")
        b.ingest(r, kv2, first)
        b.run()
        got = b.finished["q"].output
        want = DecodeEngine(model, params, capacity=1,
                            page_size=8).generate(prompt, max_new)
        assert len(want) - len(prompt) >= 64
        # greedy decode diverges PERMANENTLY at the first argmax flip,
        # so the gate is the exact-prefix length, not fraction
        # agreement.  Random-init logits are near-uniform — the
        # adversarial case for an argmax gate — and the quantized
        # handoff still carries >= 16 tokens exactly (28 measured).
        div = next((i for i, (a, e) in enumerate(zip(got, want))
                    if a != e), len(want))
        assert div - len(prompt) >= 16, (
            f"int8 KV handoff diverged after {div - len(prompt)} "
            f"greedy tokens (< 16) over a {len(want) - len(prompt)}"
            f"-token window"
        )
        agree = sum(int(a == e) for a, e in zip(got, want)) / len(want)
        assert agree >= 0.5  # post-divergence floor: not corrupted

    def test_import_validates_geometry(self, lm):
        model, params = lm
        pe = DecodeEngine(model, params, capacity=2, page_size=8)
        prompt = _prompts(21, 1, lo=5, hi=9)[0]
        slot = pe.admit(pe.prompt_bucket(len(prompt)))
        pe.prefill(slot, prompt)
        kv = pe.export_kv(slot)
        with pytest.raises(ValueError, match="page_size"):
            _cache(capacity=2, page_size=4).import_kv(kv, 32)
        de = DecodeEngine(model, params, capacity=2, page_size=8)
        with pytest.raises(ValueError, match="total_tokens"):
            de.cache.import_kv(kv, kv.length - 1)
        with pytest.raises(ValueError, match="dtype"):
            de.cache.import_kv(kv._replace(dtype="float32"), 32)
        with pytest.raises(ValueError, match="geometry"):
            de.cache.import_kv(
                kv._replace(k=kv.k[:, :, :, :2], v=kv.v[:, :, :, :2]),
                32)

    def test_allocator_invariants_after_import_churn(self, lm):
        """Import admits FRESH pages per handoff; an admit/import/
        release mix must keep the allocator's invariants and return the
        pool to empty — imports never leak or alias the exporter."""
        model, params = lm
        prompt = _prompts(41, 1, lo=9, hi=13)[0]
        pe = DecodeEngine(model, params, capacity=1, page_size=8)
        slot = pe.admit(pe.prompt_bucket(len(prompt)))
        logits = pe.prefill(slot, prompt)
        kv = pe.export_kv(slot)
        first = int(np.argmax(logits))
        de = DecodeEngine(model, params, capacity=2, page_size=8)
        total = len(prompt) + 6
        live = []
        for _ in range(8):
            kv2, _ = transfer_kv(kv, first, "none")
            live.append(de.cache.import_kv(kv2, total))
            de.cache.check_invariants()
            if len(live) == de.cache.capacity:
                de.cache.release(live.pop(0))
                de.cache.check_invariants()
        for s in live:
            de.cache.release(s)
        de.cache.check_invariants()
        assert de.cache.used_pages == 0

    def test_prefix_reregistration_on_import(self, lm):
        """The handoff's prefix chain re-registers against the IMPORTED
        pages, so a later request on the decode pool aliases them —
        prefix sharing survives the pool boundary without re-hashing
        or re-prefilling."""
        model, params = lm
        head = _prompts(55, 1, lo=8, hi=9)[0]  # exactly one page
        p1 = head + [1, 2, 3]
        p2 = head + [4, 5]
        pe = DecodeEngine(model, params, capacity=2, page_size=8)
        slot = pe.admit(pe.prompt_bucket(len(p1)))
        logits = pe.prefill(slot, p1)
        pe.cache.register_prefix(slot, p1)
        kv = pe.export_kv(slot)
        assert len(kv.prefix_chain) == 1  # the one full-page depth
        kv2, first = transfer_kv(kv, int(np.argmax(logits)), "bf16")
        de = DecodeEngine(model, params, capacity=2, page_size=8)
        b = ContinuousBatcher(de)
        r1 = Request(p1, 4, id="a")
        b.ingest(r1, kv2, first)
        m = de.cache.lookup_prefix(p2)
        assert m is not None and m.shared_len == 8
        r2 = Request(p2, 4, id="b")
        b.submit(r2)
        b.run()
        assert b.prefix_hits == 1
        assert r2.shared_len == 8
        sol = DecodeEngine(model, params, capacity=1, page_size=8)
        assert b.finished["a"].output == sol.generate(p1, 4)
        assert b.finished["b"].output == sol.generate(p2, 4)

    def test_pack_handoff_wire_bytes_exact_and_codec_validated(self):
        """The disclosed ``wire_bytes`` is EXACT: payload bytes plus 4
        per int8 scale (one absmax grid per layer per tensor) — the
        number ``attribute()`` prices and the bench fingerprints."""
        k = np.asarray(jnp.ones((2, 3, 4, 2, 2), jnp.bfloat16))
        kv = KVExport(k=k, v=k, length=10, page_size=4,
                      dtype="bfloat16", prefix_chain=())
        ph = pack_handoff(kv, 7, "bf16")
        assert ph.meta["wire_bytes"] == 2 * k.size * 2  # bf16: 2B each
        ph8 = pack_handoff(kv, 7, "int8")
        # 1 byte/elem + 4B per scale, 2 layers x 2 tensors = 4 scales
        assert ph8.meta["wire_bytes"] == 2 * k.size + 4 * 4
        kv2, first = unpack_handoff(ph)
        assert first == 7
        np.testing.assert_array_equal(_bits(kv2.k), _bits(k))
        with pytest.raises(ValueError, match="codec"):
            pack_handoff(kv, 0, "f32")

    def test_handoff_codec_path_issues_zero_collectives(self):
        """The handoff path's own pin: encode/decode are jnp-pure casts
        — a codec that grew a collective (say, a global absmax pmax)
        would put KV transfer on the interconnect's critical path."""
        from chainermn_tpu.analysis import trace_collectives
        from chainermn_tpu.comm_wire.codecs import (
            decode_buffer,
            encode_buffer,
        )

        def roundtrip(x):
            a = decode_buffer(encode_buffer(x, "bf16"))
            c = decode_buffer(encode_buffer(x, "int8"))
            return a.astype(jnp.float32) + c.astype(jnp.float32)

        tr = trace_collectives(roundtrip, jnp.ones((4, 16), jnp.bfloat16))
        assert tr.census() == {}

    def test_kv_spans_priced_by_attribute(self, lm):
        """``kv.export``/``kv.ship``/``kv.import`` spans carry exact
        byte counts and ``kv_transfer_points`` prices each leg —
        bytes, achieved B/s, duration."""
        from chainermn_tpu import observability as obs
        from chainermn_tpu.observability.attribute import (
            kv_transfer_points,
        )

        model, params = lm
        tel = obs.Telemetry(label="kv-price")
        obs.install(tel)
        try:
            pe = DecodeEngine(model, params, capacity=1, page_size=8)
            prompt = _prompts(25, 1, lo=5, hi=9)[0]
            slot = pe.admit(pe.prompt_bucket(len(prompt)))
            logits = pe.prefill(slot, prompt)
            kv = pe.export_kv(slot)
            kv2, _first = transfer_kv(kv, int(np.argmax(logits)), "bf16")
            de = DecodeEngine(model, params, capacity=1, page_size=8)
            de.ingest_kv(kv2, len(prompt) + 4)
        finally:
            obs.install(None)
        pts = kv_transfer_points(tel.timeline)
        by = {p[0]: p for p in pts}
        assert set(by) == {"kv.export", "kv.ship", "kv.import"}
        # bf16 wire over a bf16 cache: wire bytes == the raw buffer
        assert by["kv.ship"][1] == kv.k.nbytes + kv.v.nbytes
        for _name, nbytes, _rate, dur in pts:
            assert nbytes > 0
            assert dur >= 0.0

    def test_disagg_serve_bit_identical_and_handoffs_cleared(
            self, lm, tmp_path):
        """The role-pool round trip through the journal: prefill pool
        publishes, decode pool ingests, every output equals the
        unified oracle at 0 tolerance — and consumed handoffs are
        cleared once their results exist."""
        model, params = lm
        j = RequestJournal(str(tmp_path))
        docs = [Request(p, 4, id=f"d{i}")
                for i, p in enumerate(_prompts(71, 4))]
        j.submit_all(docs)
        pr = PrefillReplica(
            DecodeEngine(model, params, capacity=2, page_size=8),
            j, codec="bf16")
        assert pr.serve() == 4
        assert sorted(j.handoffs()) == sorted(r.id for r in docs)
        assert pr.wire_bytes > 0
        dr = DisaggDecodeReplica(
            DecodeEngine(model, params, capacity=2, page_size=8),
            j, handoff_timeout_s=60.0)
        dr.serve(until_complete=4, timeout_s=120.0)
        assert dr.ingested == 4 and dr.local_prefills == 0
        res = j.results()
        sol = DecodeEngine(model, params, capacity=1, page_size=8)
        for r in docs:
            assert res[r.id]["tokens"] == sol.generate(
                r.prompt, r.max_new_tokens), r.id
        assert j.handoffs() == []  # hygiene: consumed == cleared

    def test_orphaned_handoff_reprefilled_bit_identical(
            self, lm, tmp_path):
        """A handoff that never appears (its prefill replica died
        before publishing) falls back to LOCAL prefill past
        ``handoff_timeout_s`` — greedy replay from the prompt, so the
        stream still completes bit-identically with no prefill pool at
        all."""
        model, params = lm
        j = RequestJournal(str(tmp_path))
        docs = [Request(p, 3, id=f"o{i}")
                for i, p in enumerate(_prompts(81, 3))]
        j.submit_all(docs)
        dr = DisaggDecodeReplica(
            DecodeEngine(model, params, capacity=2, page_size=8),
            j, handoff_timeout_s=0.0)
        dr.serve(until_complete=3, timeout_s=120.0)
        assert dr.local_prefills == 3 and dr.ingested == 0
        res = j.results()
        sol = DecodeEngine(model, params, capacity=1, page_size=8)
        for r in docs:
            assert res[r.id]["tokens"] == sol.generate(
                r.prompt, r.max_new_tokens), r.id

    def test_dead_prefill_share_rederives_on_pool_drain(
            self, lm, tmp_path):
        """Marking a prefill replica draining (pool="prefill")
        re-routes its unpublished share onto the healthy prefill
        replicas — the same claim algebra the decode pool uses, scoped
        to the prefill marker namespace."""
        model, params = lm
        j = RequestJournal(str(tmp_path))
        docs = [Request(p, 2, id=f"s{i}")
                for i, p in enumerate(_prompts(61, 4))]
        j.submit_all(docs)
        p1 = PrefillReplica(
            DecodeEngine(model, params, capacity=2, page_size=8),
            j, replica_index=1, n_replicas=2)
        assert p1.serve() == 2  # its own share: seq 1 and 3
        assert len(j.handoffs()) == 2
        j.mark_draining(0, pool="prefill")
        assert p1.serve() == 4  # re-derived the dead replica's share
        assert sorted(j.handoffs()) == sorted(r.id for r in docs)

    def test_pool_scoped_drain_markers_are_disjoint(self, tmp_path):
        """Prefill-pool drains must not re-route decode-pool claims
        (and vice versa): the marker namespaces are disjoint by
        construction, and a pool name that could collide with the
        default digit namespace is rejected."""
        j = RequestJournal(str(tmp_path))
        j.mark_draining(0, pool="prefill")
        assert j.draining() == []
        assert j.draining(pool="prefill") == [0]
        j.mark_draining(1)
        assert j.draining() == [1]
        assert j.draining(pool="prefill") == [0]
        j.clear_draining(0, pool="prefill")
        assert j.draining(pool="prefill") == []
        assert j.draining() == [1]
        with pytest.raises(ValueError, match="alphabetic"):
            j.mark_draining(0, pool="pre_fill")

    def test_oversize_request_fails_loudly_in_prefill_pool(
            self, lm, tmp_path):
        """A request no decode-pool engine could ever admit fails
        LOUDLY at the prefill pool (result written, stream not
        wedged) — the unified replica's contract, kept across the
        split."""
        model, params = lm
        j = RequestJournal(str(tmp_path))
        j.submit_all([Request(list(range(5)), 500, id="big"),
                      Request([1, 2, 3], 2, id="ok")])
        pr = PrefillReplica(
            DecodeEngine(model, params, capacity=2, page_size=8), j)
        assert pr.serve() == 1  # "ok" published; "big" failed loudly
        res = j.results()
        assert res["big"]["state"] == "failed"
        assert "max_total" in res["big"]["error"]
        assert j.handoffs() == ["ok"]

    def test_ttft_splits_into_queue_plus_prefill(self, lm):
        """``serving.ttft`` decomposes into ``.queue`` (submit ->
        prefill start) + ``.prefill`` (prefill start -> first token):
        same timestamps, so the single-request algebra is exact — and
        under a capacity-1 backlog the wait lands in the QUEUE term,
        the split disaggregation exists to expose."""
        model, params = lm
        eng = DecodeEngine(model, params, capacity=1, page_size=8)
        b = ContinuousBatcher(eng)
        b.serve([Request(p, 3, id=f"t{i}")
                 for i, p in enumerate(_prompts(13, 3))])
        rep = b.latency_report()
        for key in ("serving.ttft", "serving.ttft.queue",
                    "serving.ttft.prefill"):
            assert rep[key]["n"] == 3, key
        assert rep["serving.ttft.queue"]["p99_ms"] > 0
        b2 = ContinuousBatcher(
            DecodeEngine(model, params, capacity=1, page_size=8))
        b2.serve([Request([5, 4, 3], 2, id="solo")])
        r2 = b2.latency_report()
        assert r2["serving.ttft"]["p50_ms"] == pytest.approx(
            r2["serving.ttft.queue"]["p50_ms"]
            + r2["serving.ttft.prefill"]["p50_ms"], abs=1e-3)

    def test_dense_oracle_and_bad_codec_rejected(self, lm, tmp_path):
        model, params = lm
        dense = DecodeEngine(model, params, capacity=2, layout="dense")
        j = RequestJournal(str(tmp_path))
        with pytest.raises(ValueError, match="dense"):
            PrefillReplica(dense, j)
        with pytest.raises(ValueError, match="dense"):
            DisaggDecodeReplica(dense, j)
        with pytest.raises(ValueError, match="paged-layout"):
            dense.export_kv(0)
        paged = DecodeEngine(model, params, capacity=2, page_size=8)
        with pytest.raises(ValueError, match="codec"):
            PrefillReplica(paged, j, codec="zstd")

    def test_pending_memoized_by_directory_signature(self, tmp_path):
        """ISSUE 18 bugfix pin: ``pending()`` rescans only when the
        req/res name signature changes — replicas poll it every round,
        and the old always-rescan turned the poll loop O(requests) in
        json loads."""
        j = RequestJournal(str(tmp_path))
        j.submit_all([Request([1, 2], 2, id=f"m{i}") for i in range(3)])
        base = j._pending_scans
        assert len(j.pending()) == 3
        j.pending()
        j.pending()
        assert j._pending_scans == base + 1  # repeats hit the memo
        j.submit(Request([3], 1, id="m3"))
        assert len(j.pending()) == 4
        assert j._pending_scans == base + 2  # new request -> rescan
        j.write_result(Request([1, 2], 2, id="m0"))
        assert len(j.pending()) == 3
        assert j._pending_scans == base + 3  # new result -> rescan
        j.pending()
        assert j._pending_scans == base + 3


# ----------------------------------------------------------------------
# mnlint: serving is NOT part of the sanctioned comm layer
# ----------------------------------------------------------------------
class TestServingLint:
    """ISSUE 13 satellite: the serving tier routes every collective
    through the audited wrappers (``parallel``/``functions.collectives``
    layers) — it is NOT sanctioned for raw ``lax.psum``-family calls,
    and the subsystem self-lints clean under the repo gate."""

    def test_serving_is_not_sanctioned(self):
        from chainermn_tpu.analysis.lint import SANCTIONED

        assert not any(
            p.startswith("chainermn_tpu/serving") for p in SANCTIONED
        ), "serving/ must never join the raw-psum sanctioned list"

    def test_serving_modules_lint_clean(self):
        from chainermn_tpu.analysis.lint import repo_root, run_lint

        root = repo_root()
        target = os.path.join(root, "chainermn_tpu", "serving")
        violations = run_lint([target], root=root)
        assert violations == [], "\n".join(
            f"{v.path}:{v.line}: {v.rule}: {v.message}"
            for v in violations
        )

    def test_raw_psum_in_serving_would_be_flagged(self, tmp_path):
        """Behavioral pin of the not-sanctioned claim: a raw collective
        dropped into a serving module trips the repo gate."""
        from chainermn_tpu.analysis.lint import run_lint

        bad = tmp_path / "chainermn_tpu" / "serving" / "sneaky.py"
        bad.parent.mkdir(parents=True)
        bad.write_text(
            "import jax.lax\n"
            "def f(x):\n"
            "    return jax.lax.psum(x, 'tp')\n"
        )
        violations = run_lint([str(bad)], root=str(tmp_path))
        assert [v.rule for v in violations] == ["raw-collective"]


# ----------------------------------------------------------------------
# decode_bench rungs: CI smoke on the CPU mesh + perf_history direction
# ----------------------------------------------------------------------
class TestDecodeBenchCI:
    def test_decode_rungs_emit_protocol_json_on_cpu_mesh(self, tmp_path):
        """Acceptance: ``decode_bs1``/``decode_saturated`` run on the
        8-virtual-device CPU mesh and print per-rung JSON carrying the
        min-of-N protocol fields plus the serving fingerprints (the
        ``decode_step`` budget verdict, the decode program's authored
        census + trace hash, capacity/page geometry) — and every row's
        metric resolves HIGHER-better under perf_history's direction
        heuristic (the ``tokens_per_sec_per_chip`` unit contains the
        ``sec_per`` substring trap).  Tiny shapes via the HUNT_* knobs:
        a smoke of the harness, not a measurement."""
        import json as _json
        import subprocess
        import sys

        from conftest import subprocess_env

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = subprocess_env(8)
        env.update({
            "HUNT_DECODE_TOKENS": "2", "HUNT_REPEATS": "2",
            "HUNT_DECODE_CAPACITY": "2", "HUNT_SERVE_DMODEL": "32",
            "HUNT_SERVE_LAYERS": "2", "HUNT_SERVE_HEADS": "4",
            "HUNT_SERVE_VOCAB": "64", "HUNT_SERVE_PROMPT": "4",
            "HUNT_SERVE_PAGE": "8",
        })
        proc = subprocess.run(
            [sys.executable,
             os.path.join(repo, "benchmarks", "decode_bench.py"),
             "--cpu-mesh"],
            env=env, capture_output=True, text=True, timeout=560,
            cwd=tmp_path,
        )
        assert proc.returncode == 0, (
            f"decode_bench exited {proc.returncode}\n"
            f"--- stdout ---\n{proc.stdout[-3000:]}\n"
            f"--- stderr ---\n{proc.stderr[-3000:]}"
        )
        sys.path.insert(0, os.path.join(repo, "benchmarks"))
        try:
            from perf_history import lower_is_better
        finally:
            sys.path.pop(0)
        recs = {}
        for line in proc.stdout.splitlines():
            if line.startswith("{"):
                r = _json.loads(line)
                assert "error" not in r, r
                recs[r["metric"]] = r
        want = {"decode_bs1_tokens_per_sec_per_chip",
                "decode_saturated_tokens_per_sec_per_chip",
                "decode_prefix_shared_tokens_per_sec_per_chip",
                "decode_prefix_cold_tokens_per_sec_per_chip",
                "decode_spec_k4_tokens_per_sec_per_chip",
                "decode_spec_off_tokens_per_sec_per_chip",
                "decode_disagg_on_tokens_per_sec_per_chip",
                "decode_disagg_off_tokens_per_sec_per_chip"}
        assert want <= set(recs), sorted(recs)
        for name in want:
            r = recs[name]
            # a noisy CI host can land every paired difference
            # non-positive: the bench then reports a DISCLOSED null
            # (perf_history skips null rows) — never a negative rate
            if r["noise_floor"]:
                assert r["value"] is None
            else:
                assert r["value"] > 0
            assert r["unit"] == "tokens_per_sec_per_chip"
            assert r["n_measurements"] == 2
            # serving fingerprints: the budget pin's verdict rides
            # every row, so a capture where the program grew a
            # collective reads as a config change, not noise
            assert r["budget"] == "decode_step"
            assert r["budget_within"] is True
            # the CPU smoke serves the non-TP engine: zero authored
            # collectives (the census is {}), trivially within budget —
            # the trace hash still fingerprints the program
            assert r["decode_census"] == {}
            assert len(r["decode_trace_hash"]) == 12
            assert r["page_size"] == 8
            # gated direction-aware: higher-better despite "sec_per"
            assert not lower_is_better(name, r)
        assert recs["decode_bs1_tokens_per_sec_per_chip"]["capacity"] == 1
        assert recs[
            "decode_saturated_tokens_per_sec_per_chip"]["capacity"] == 2
        # prefix-sharing A/B pair: the shared rung actually aliased
        # pages and fingerprints the distinct-page saving vs its own
        # cold leg; the cold rung shares nothing (deterministic serve,
        # so the two rungs' peaks reconcile exactly)
        shared = recs["decode_prefix_shared_tokens_per_sec_per_chip"]
        cold = recs["decode_prefix_cold_tokens_per_sec_per_chip"]
        assert shared["share_prefixes"] is True
        assert cold["share_prefixes"] is False
        assert shared["prefix_hits"] >= 1
        assert cold["prefix_hits"] == 0
        assert shared["pages_saved"] >= 1
        assert (shared["peak_used_pages"] + shared["pages_saved"]
                == cold["peak_used_pages"])
        # speculative A/B pair: the k=4 rung reports its acceptance
        # rate and the verify program's pinned budget verdict; the off
        # rung is the plain-decode control (no spec fields)
        spec = recs["decode_spec_k4_tokens_per_sec_per_chip"]
        assert spec["spec_k"] == 4
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
        assert spec["verify_steps"] > 0
        assert spec["spec_budget"] == "spec_verify_step"
        assert spec["spec_budget_within"] is True
        assert spec["verify_census"] == {}  # non-TP smoke: authored 0
        assert len(spec["verify_trace_hash"]) == 12
        assert "spec_k" not in recs[
            "decode_spec_off_tokens_per_sec_per_chip"]
        # disaggregation A/B pair: the on rung serves the same mixed
        # stream through role pools and fingerprints the handoff
        # (codec, exact wire bytes, count) plus the prefill program's
        # own pinned budget; both legs split TTFT into queue/prefill
        don = recs["decode_disagg_on_tokens_per_sec_per_chip"]
        doff = recs["decode_disagg_off_tokens_per_sec_per_chip"]
        assert don["disagg"] is True
        assert doff["disagg"] is False
        assert don["handoff_codec"] == "bf16"
        assert doff["handoff_codec"] is None
        assert don["handoff_bytes"] > 0
        assert don["n_handoffs"] == 4  # 2 * HUNT_DECODE_CAPACITY
        for leg in (don, doff):
            assert leg["prefill_budget"] == "prefill_step"
            assert leg["prefill_budget_within"] is True
            assert leg["prefill_census"] == {}  # non-TP smoke
            for f in ("ttft_p50_ms", "ttft_p99_ms",
                      "ttft_queue_p50_ms", "ttft_prefill_p50_ms"):
                assert f in leg, f
        # the ingest phase only exists on the disaggregated leg
        assert "ingest_p50_ms" in don
        assert "ingest_p50_ms" not in doff

"""Bucket-granularity comm/compute overlap engine (ISSUE 8).

Tentpole pins, in order of load-bearingness:

* the overlapped step is BIT-IDENTICAL to the synchronous bucketed
  step — params, opt state, error-feedback residuals, losses — for
  every codec (the pass only reorders equations: same buckets, same
  codec, same summands, same per-collective reduction order);
* the collective census is UNCHANGED (the existing analysis budget
  pins pass on the overlapped program without edits) — only the trace
  ordering moves;
* ordering: in the scheduled program every bucket psum is issued at
  its dependency frontier (``delay == 0`` — dispatched before the
  remaining backward segments complete), checked by the new
  ordering-aware ``analysis.check_overlap``; the synchronous program
  FAILS that check for any multi-bucket plan;
* segment/bucket alignment: the program carries exactly one fused
  psum per plan bucket, issue order follows backward readiness
  (reverse-planner order on a sequential model), and consecutive
  bucket issues are separated by real backward compute (the segments
  the scheduler threads the collectives through);
* ``plan_hash()`` is untouched by the overlap mode (the plan is a pure
  function of shapes; overlap is a schedule, not a wire).

Satellites: the host-staged eager tier's pipelined bucket exchanges
equal the serial schedule bit-for-bit; overlap composes with ZeRO
(reduce-scatter/all-gather census unchanged) and is rejected on the
GSPMD path and under double buffering.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

import chainermn_tpu as cmn
from chainermn_tpu import comm_wire as cw
from chainermn_tpu.analysis import check_overlap, enforce
from chainermn_tpu.comm_wire import (
    WireConfig,
    assert_overlap_order,
    bucket_issue_report,
    issue_report,
    plan_of_tree,
    resolve_overlap,
    schedule_jaxpr,
)
from chainermn_tpu.comm_wire.overlap import OverlappedStep
from chainermn_tpu.optimizers import build_train_step


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("tpu", devices=devices8)


def _assert_tree_bit_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        assert jnp.dtype(x.dtype) == jnp.dtype(y.dtype)
        np.testing.assert_array_equal(
            np.asarray(x, np.float64) if x.dtype == jnp.bfloat16
            else np.asarray(x),
            np.asarray(y, np.float64) if y.dtype == jnp.bfloat16
            else np.asarray(y),
        )


def _mlp3_setup(comm, wire, overlap, tx=None, n_steps=5):
    """3-layer MLP regression fixture shared by the bit-identity and
    ordering tests; returns (params, opt_state, step, batch, losses)."""
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 8) * 0.3, jnp.float32),
        "w3": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32),
    }
    w_true = rng.randn(8, 4).astype(np.float32)
    x = rng.randn(32, 8).astype(np.float32)
    y = x @ w_true

    def loss_fn(p, b):
        bx, by = b
        h = jnp.tanh(bx @ p["w1"])
        return jnp.mean((jnp.tanh(h @ p["w2"]) @ p["w3"] - by) ** 2)

    opt = cmn.create_multi_node_optimizer(
        tx or optax.adam(1e-2), comm, wire=wire, overlap=overlap
    )
    step = build_train_step(comm, loss_fn, opt, donate=False)
    p, o = step.place(params, opt.init(params))
    batch = (
        jax.device_put(x, step.batch_sharding),
        jax.device_put(y, step.batch_sharding),
    )
    losses = []
    for _ in range(n_steps):
        p, o, m = step(p, o, batch)
        losses.append(float(m["loss"]))
    return p, o, step, batch, losses


# tiny buckets => one bucket per leaf: genuinely multi-bucket programs
_TINY = dict(bucket_bytes=64, max_buckets=0)


# ----------------------------------------------------------------------
# bit identity: overlapped == synchronous, all codecs
# ----------------------------------------------------------------------
class TestBitIdentity:
    @pytest.mark.parametrize("wire", [
        "auto",
        "per_leaf",
        WireConfig(codec="bf16", **_TINY),
        WireConfig(codec="f16", **_TINY),
        WireConfig(codec="int8", **_TINY),
    ])
    def test_overlapped_equals_synchronous_exactly(self, comm, wire):
        """Acceptance: 0 tolerance across params, opt state, and the
        per-step losses — the pass reorders, never recomputes."""
        ps, os_, _, _, ls = _mlp3_setup(comm, wire, "none")
        pb, ob, _, _, lb = _mlp3_setup(comm, wire, "bucket")
        _assert_tree_bit_equal(ps, pb)
        _assert_tree_bit_equal(os_, ob)
        assert ls == lb

    def test_int8_error_feedback_residual_carry_identical(self, comm):
        """The EF residual (flat wire buckets in the optimizer state)
        rides the same reordered program: bit-identical carry."""
        wire = WireConfig(codec="int8", error_feedback=True, **_TINY)
        ps, os_, _, _, ls = _mlp3_setup(comm, wire, "none")
        pb, ob, _, _, lb = _mlp3_setup(comm, wire, "bucket")
        assert isinstance(ob.wire_residual, tuple) and ob.wire_residual
        _assert_tree_bit_equal(os_.wire_residual, ob.wire_residual)
        _assert_tree_bit_equal(ps, pb)
        assert ls == lb

    def test_zero_redundancy_overlap_identical(self, comm):
        ps, os_, _, _, ls = _mlp3_setup(
            comm, "bf16", "none",
            tx=optax.adam(1e-2),
        )
        # same fixture through the ZeRO wrapper, overlap on/off
        outs = {}
        for mode in ("none", "bucket"):
            rng = np.random.RandomState(0)
            params = {
                "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
                "w2": jnp.asarray(rng.randn(16, 8) * 0.3, jnp.float32),
                "w3": jnp.asarray(rng.randn(8, 4) * 0.3, jnp.float32),
            }
            w_true = rng.randn(8, 4).astype(np.float32)
            x = rng.randn(32, 8).astype(np.float32)
            y = x @ w_true

            def loss_fn(p, b):
                bx, by = b
                h = jnp.tanh(bx @ p["w1"])
                return jnp.mean(
                    (jnp.tanh(h @ p["w2"]) @ p["w3"] - by) ** 2
                )

            opt = cmn.create_multi_node_optimizer(
                optax.adam(1e-2), comm, zero_redundancy=True,
                wire="bf16", overlap=mode,
            )
            step = build_train_step(comm, loss_fn, opt, donate=False)
            p, o = step.place(params, opt.init(params))
            batch = (
                jax.device_put(x, step.batch_sharding),
                jax.device_put(y, step.batch_sharding),
            )
            for _ in range(5):
                p, o, m = step(p, o, batch)
            tr = step.collective_trace(p, o, batch)
            outs[mode] = (p, o, tr)
        pn, on, tn = outs["none"]
        pb, ob, tb = outs["bucket"]
        _assert_tree_bit_equal(pn, pb)
        _assert_tree_bit_equal(on, ob)
        # ZeRO census unchanged: reduce_scatter down + all_gather up
        assert tn.census() == tb.census()
        assert tb.count("reduce_scatter") >= 1
        assert tb.count("all_gather") >= 1


# ----------------------------------------------------------------------
# census unchanged, ordering moved
# ----------------------------------------------------------------------
class TestCensusAndOrdering:
    def _mnist_step(self, comm, overlap):
        from chainermn_tpu.models import MLP

        model = MLP(n_units=1000)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))

        def loss_fn(p, b):
            x, y = b
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x), y
            ).mean()

        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05), comm, overlap=overlap
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = (
            jax.device_put(jnp.zeros((64, 28, 28)), step.batch_sharding),
            jax.device_put(jnp.zeros((64,), jnp.int32),
                           step.batch_sharding),
        )
        return step, p, o, batch, params

    def test_census_unchanged_budget_pin_passes_as_is(self, comm):
        """Acceptance: the lowered census is unchanged — the EXISTING
        mlp budget pin enforces the overlapped trace without edits."""
        step_s, p, o, batch, params = self._mnist_step(comm, "none")
        step_b, pb, ob, batch_b, _ = self._mnist_step(comm, "bucket")
        tr_s = step_s.collective_trace(p, o, batch)
        tr_b = step_b.collective_trace(pb, ob, batch_b)
        assert tr_s.census() == tr_b.census()
        plan = plan_of_tree(params)
        assert tr_b.count("all_reduce") == plan.n_buckets + 1
        enforce("mlp_train_step", tr_b)  # the pre-existing pin, as-is

    def test_only_ordering_moves(self, comm):
        """Same multiset of record signatures, different sequence."""
        step_s, p, o, batch, _ = self._mnist_step(comm, "none")
        step_b, pb, ob, batch_b, _ = self._mnist_step(comm, "bucket")
        tr_s = step_s.collective_trace(p, o, batch)
        tr_b = step_b.collective_trace(pb, ob, batch_b)
        sig_s = [r.signature() for r in tr_s.records]
        sig_b = [r.signature() for r in tr_b.records]
        assert sorted(sig_s) == sorted(sig_b)
        assert sig_s != sig_b
        assert tr_s.trace_hash() != tr_b.trace_hash()

    def test_census_agrees_with_lowered_hlo(self, comm):
        """The walker counts the same overlapped program XLA lowers
        (the analyzer stays a first-class citizen of the new shape)."""
        from chainermn_tpu.analysis import assert_census_agreement

        step, p, o, batch, _ = self._mnist_step(comm, "bucket")
        tr = step.collective_trace(p, o, batch)
        txt = step.get_jitted(p, o).lower(p, o, batch).as_text()
        assert_census_agreement(tr, txt)

    def test_overlap_check_passes_on_scheduled_program(self, comm):
        step, p, o, batch, params = self._mnist_step(comm, "bucket")
        plan = plan_of_tree(params)
        assert plan.n_buckets >= 2
        jb = step.get_jitted(p, o).scheduled_jaxpr(p, o, batch)
        assert check_overlap(jb, plan) == []
        assert_overlap_order(jb, plan)  # assert-style spelling

    def test_overlap_check_fails_on_synchronous_program(self, comm):
        """The ordering-aware check is not vacuous: the synchronous
        multi-bucket program queues psums at the tail and FAILS."""
        step, p, o, batch, params = self._mnist_step(comm, "none")
        plan = plan_of_tree(params)
        closed = jax.make_jaxpr(step.get_jitted(p, o))(p, o, batch)
        findings = check_overlap(closed, plan)
        assert findings and all(f.severity == "error" for f in findings)
        with pytest.raises(AssertionError, match="issued late"):
            assert_overlap_order(closed, plan)

    def test_overlap_check_flags_missing_buckets(self, comm):
        """A program that does not carry the plan's fused reductions is
        an error, not a silent pass."""
        plan = plan_of_tree({"w": jnp.zeros((128,))})
        closed = jax.make_jaxpr(lambda x: x + 1)(jnp.zeros((4,)))
        findings = check_overlap(closed, plan)
        assert any("does not carry" in f.message for f in findings)

    def test_trace_guard_hash_agrees_per_mode(self, comm):
        """verify_collective_trace works on the overlapped step (the
        divergence guard is keyed per compiled program variant, and the
        overlapped variant hashes consistently)."""
        step, p, o, batch, _ = self._mnist_step(comm, "bucket")
        h1 = step.verify_collective_trace(p, o, batch)
        h2 = step.collective_trace(p, o, batch).trace_hash()
        assert h1 == h2


# ----------------------------------------------------------------------
# segment / bucket alignment
# ----------------------------------------------------------------------
class TestSegmentAlignment:
    def _aligned(self, step, p, o, batch, plan):
        """Common alignment pins: one fused psum per bucket, all at
        their dependency frontier, separated by real backward compute
        (the per-bucket segments)."""
        jb = step.get_jitted(p, o).scheduled_jaxpr(p, o, batch)
        recs = bucket_issue_report(jb, plan)
        assert len(recs) == plan.n_buckets
        assert all(r.delay == 0 for r in recs)
        # consecutive bucket issues are separated by >= 1 equation (the
        # pack of the next bucket at minimum, its backward segment in
        # general): the psums did NOT collapse into one tail cluster
        idx = sorted(r.index for r in recs)
        if len(idx) > 1:
            assert all(b - a > 1 for a, b in zip(idx, idx[1:]))
        return recs

    def test_mlp_per_layer_buckets_reverse_planner_order(self, comm):
        """On a sequential model with one bucket per layer, issue order
        is REVERSE planner order: backward finalizes the last layer's
        leaves first, so its bucket's psum dispatches first."""
        from chainermn_tpu.models import MLP

        model = MLP(n_units=64)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))
        wire = WireConfig(codec="none", bucket_bytes=8, max_buckets=0)
        plan = plan_of_tree(params, wire.bucket_bytes, wire.max_buckets)
        assert plan.n_buckets == plan.n_leaves  # one bucket per leaf

        def loss_fn(p, b):
            x, y = b
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x), y
            ).mean()

        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05), comm, wire=wire, overlap="bucket"
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = (
            jax.device_put(jnp.zeros((16, 28, 28)), step.batch_sharding),
            jax.device_put(jnp.zeros((16,), jnp.int32),
                           step.batch_sharding),
        )
        recs = self._aligned(step, p, o, batch, plan)
        # map issue order back to plan order via the (distinct) kernel
        # bucket sizes: Dense_0 784*64, Dense_1 64*64, Dense_2 64*10
        sizes_by_issue = [
            r.operand_shapes[0][0]
            for r in sorted(recs, key=lambda r: r.index)
        ]
        k0, k1, k2 = 784 * 64, 64 * 64, 64 * 10
        assert sizes_by_issue.index(k2) < sizes_by_issue.index(k1)
        assert sizes_by_issue.index(k1) < sizes_by_issue.index(k0)

    def test_resnet50_alignment_and_pinned_budget(self, comm):
        """ResNet-50: the default plan's buckets all issue at their
        frontier and the EXISTING resnet50 budget pin (<= 8 all-reduce)
        enforces the overlapped trace unchanged — 5 psums (4 buckets +
        loss pmean), only reordered."""
        from chainermn_tpu.models import ResNet50

        model = ResNet50(num_classes=1000, train=False)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))
        )
        plan = plan_of_tree(params)
        assert plan.n_buckets >= 2

        def loss_fn(p, b):
            x, y = b
            return optax.softmax_cross_entropy_with_integer_labels(
                model.apply(p, x), y
            ).mean()

        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05), comm, overlap="bucket"
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = (
            jax.device_put(jnp.zeros((8, 32, 32, 3)),
                           step.batch_sharding),
            jax.device_put(jnp.zeros((8,), jnp.int32),
                           step.batch_sharding),
        )
        self._aligned(step, p, o, batch, plan)
        tr = step.collective_trace(p, o, batch)
        assert tr.count("all_reduce") == plan.n_buckets + 1
        enforce("resnet50_train_step", tr)  # the pre-existing pin

    def test_transformer_alignment_and_pinned_budget(self, comm):
        from chainermn_tpu.models.transformer import TransformerLM, lm_loss

        model = TransformerLM(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2,
            max_len=64, dtype=jnp.float32,
        )
        toks = jnp.zeros((8, 16), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks[:1])
        # force a multi-bucket plan on the tiny fixture while staying
        # inside the wire's promised <= 6-bucket ceiling (the budget
        # pin enforces buckets + loss pmean <= 8)
        wire = WireConfig(codec="none", bucket_bytes=16 * 1024)
        plan = plan_of_tree(params, wire.bucket_bytes, wire.max_buckets)
        assert plan.n_buckets >= 2

        def loss_fn(p, b):
            return lm_loss(model.apply(p, b), b)

        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), comm, wire=wire, overlap="bucket"
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(toks, step.batch_sharding)
        self._aligned(step, p, o, batch, plan)
        enforce("transformer_train_step",
                step.collective_trace(p, o, batch))

    def test_int8_scale_pmax_stays_single_and_first(self, comm):
        """int8's batched absmax pmax remains ONE collective (census
        contract) and — depending on every bucket — necessarily issues
        before any int8 payload psum."""
        wire = WireConfig(codec="int8", **_TINY)
        p, o, step, batch, _ = _mlp3_setup(comm, wire, "bucket",
                                           n_steps=1)
        tr = step.collective_trace(p, o, batch)
        pmaxes = [r for r in tr.records if r.primitive == "pmax"]
        assert len(pmaxes) == 1
        order = [r.primitive for r in tr.records]
        int8_psums = [
            i for i, r in enumerate(tr.records)
            if r.primitive == "psum" and r.dtypes
            and r.dtypes[0] == "int32"
        ]
        assert order.index("pmax") < min(int8_psums)


# ----------------------------------------------------------------------
# plan hash / agreement untouched by the overlap mode
# ----------------------------------------------------------------------
class TestPlanHashUnaffected:
    def test_plan_is_mode_independent(self, comm):
        params = {"a": jnp.zeros((300,)), "b": jnp.zeros((40, 5))}
        opts = {
            mode: cmn.create_multi_node_optimizer(
                optax.sgd(0.1), comm, overlap=mode
            )
            for mode in ("none", "bucket")
        }
        plans = {
            mode: plan_of_tree(
                params, o.wire.bucket_bytes, o.wire.max_buckets
            )
            for mode, o in opts.items()
        }
        assert plans["none"].plan_hash() == plans["bucket"].plan_hash()

    def test_plan_agreement_guard_runs_identically(self, monkeypatch,
                                                   comm):
        """optimizer.init's plan_agreement sees the same hash either
        way — overlap is a schedule, not a wire layout."""
        seen = {}

        def fake_agreement(c, plan, **kw):
            seen.setdefault("hashes", []).append(plan.plan_hash())
            return plan.plan_hash()

        monkeypatch.setattr(cw, "plan_agreement", fake_agreement)
        monkeypatch.setattr(comm.__class__, "process_count", 2,
                            raising=False)
        params = {"w": jnp.zeros((64,))}
        for mode in ("none", "bucket"):
            opt = cmn.create_multi_node_optimizer(
                optax.sgd(0.1), comm, overlap=mode
            )
            opt.init(params)
        monkeypatch.undo()
        assert len(seen["hashes"]) == 2
        assert seen["hashes"][0] == seen["hashes"][1]


# ----------------------------------------------------------------------
# engine plumbing
# ----------------------------------------------------------------------
class TestEngine:
    def test_resolve_overlap_forms(self):
        assert resolve_overlap(None) == "none"
        assert resolve_overlap("none") == "none"
        assert resolve_overlap("bucket") == "bucket"
        with pytest.raises(ValueError, match="overlap"):
            resolve_overlap("layer")

    def test_double_buffering_rejected(self, comm):
        with pytest.raises(ValueError, match="double_buffering"):
            cmn.create_multi_node_optimizer(
                optax.sgd(0.1), comm, double_buffering=True,
                overlap="bucket",
            )

    def test_gspmd_path_rejected(self, comm):
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), comm, overlap="bucket"
        )
        with pytest.raises(ValueError, match="use_shard_map"):
            build_train_step(
                comm, lambda p, b: jnp.sum(p["w"] * b), opt,
                use_shard_map=False, donate=False,
            )

    def test_schedule_jaxpr_is_pure_reorder(self):
        """Unit: same equation multiset, topological validity, value
        identity on a hand-built program with a fake 'collective'-free
        body (no collectives => unchanged at that level)."""
        def f(x):
            a = x * 2
            b = a + 1
            return b * a

        closed = jax.make_jaxpr(f)(jnp.zeros((4,)))
        out = schedule_jaxpr(closed)
        assert [e.primitive.name for e in out.jaxpr.eqns] == [
            e.primitive.name for e in closed.jaxpr.eqns
        ]

    def test_overlapped_step_caches_per_signature(self, comm):
        p, o, step, batch, _ = _mlp3_setup(comm, "auto", "bucket",
                                           n_steps=1)
        inner = step.get_jitted(p, o)
        assert isinstance(inner, OverlappedStep)
        n0 = len(inner._cache)
        inner(p, o, batch)
        inner(p, o, batch)
        assert len(inner._cache) == n0  # no retrace on same signature

    def test_overlapped_step_donation(self, comm):
        """donate=True consumes params/opt_state buffers on the second
        call (the first call's outputs feed the next), proving the flat
        donation mapping is live."""
        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}

        def loss_fn(p, b):
            return jnp.mean((b @ p["w"]) ** 2)

        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05), comm, overlap="bucket"
        )
        step = build_train_step(comm, loss_fn, opt)  # donate=True
        p, o = step.place(params, opt.init(params))
        batch = jax.device_put(
            jnp.asarray(rng.randn(16, 8), jnp.float32),
            step.batch_sharding,
        )
        p1, o1, _ = step(p, o, batch)
        p2, o2, _ = step(p1, o1, batch)
        assert jax.tree_util.tree_leaves(p1)[0].is_deleted()
        assert not jax.tree_util.tree_leaves(p2)[0].is_deleted()

    def test_issue_report_walks_nested_jaxprs(self, comm):
        p, o, step, batch, _ = _mlp3_setup(comm, "auto", "bucket",
                                           n_steps=1)
        # from the OUTER (jit-wrapped) program: the walker descends
        # pjit -> shard_map and still finds every collective
        closed = jax.make_jaxpr(step.get_jitted(p, o))(p, o, batch)
        recs = issue_report(closed)
        assert any(r.primitive == "psum" for r in recs)
        assert all(r.context for r in recs)  # all nested, none top-level

    def test_accum_steps_compose(self, comm):
        """Gradient accumulation (scan) composes: the scan body is left
        untouched, the post-scan bucket psums still overlap-schedule,
        numerics bit-identical."""
        rng = np.random.RandomState(0)
        params = {"w": jnp.asarray(rng.randn(8, 4), jnp.float32)}
        x = rng.randn(32, 8).astype(np.float32)
        y = (x @ rng.randn(8, 4)).astype(np.float32)

        def loss_fn(p, b):
            bx, by = b
            return jnp.mean((bx @ p["w"] - by) ** 2)

        outs = {}
        for mode in ("none", "bucket"):
            opt = cmn.create_multi_node_optimizer(
                optax.sgd(0.05), comm, overlap=mode
            )
            step = build_train_step(
                comm, loss_fn, opt, accum_steps=2, donate=False
            )
            p, o = step.place(params, opt.init(params))
            batch = (
                jax.device_put(x, step.batch_sharding),
                jax.device_put(y, step.batch_sharding),
            )
            for _ in range(3):
                p, o, m = step(p, o, batch)
            outs[mode] = p
        _assert_tree_bit_equal(outs["none"], outs["bucket"])


# ----------------------------------------------------------------------
# bench rungs CI smoke
# ----------------------------------------------------------------------
class TestOverlapBenchRungsCI:
    def test_overlap_rungs_emit_protocol_json_on_cpu_mesh(self,
                                                          tmp_path):
        """Acceptance: the ``overlap_off/on`` A/B runs on the
        8-virtual-device CPU mesh and prints per-rung JSON carrying the
        min-of-N protocol fields plus the overlap/wire provenance —
        measurement-ready for the next TPU capture.  Tiny shapes via
        the HUNT_* knobs: a smoke of the harness, not a measurement."""
        import json as _json
        import os
        import subprocess
        import sys

        from conftest import subprocess_env

        repo = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        env = subprocess_env(8)
        env.update({"HUNT_MLP_UNITS": "32", "HUNT_MLP_BATCH": "8",
                    "HUNT_K": "4", "HUNT_REPEATS": "2"})
        rungs = ["overlap_off", "overlap_on"]
        proc = subprocess.run(
            [sys.executable,
             os.path.join(repo, "benchmarks", "comm_overlap_bench.py"),
             "--cpu-mesh", *rungs],
            env=env, capture_output=True, text=True, timeout=420,
            cwd=tmp_path,
        )
        assert proc.returncode == 0, (
            f"comm_overlap_bench exited {proc.returncode}\n"
            f"--- stdout ---\n{proc.stdout[-3000:]}\n"
            f"--- stderr ---\n{proc.stderr[-3000:]}"
        )
        recs = {}
        for line in proc.stdout.splitlines():
            if line.startswith("{"):
                r = _json.loads(line)
                if "variant" in r:
                    recs[r["variant"]] = r
        assert set(rungs) <= set(recs), (rungs, sorted(recs))
        for name in rungs:
            r = recs[name]
            assert r["n_measurements"] >= 2, r
            if len([s for s in r["samples_ms"] if s > 0]) >= 2:
                assert "spread_max_over_min" in r, r
        assert recs["overlap_off"]["overlap"] == "none"
        assert recs["overlap_on"]["overlap"] == "bucket"
        # identical wire either side: the A/B isolates pure scheduling
        assert (recs["overlap_on"]["wire_buckets"]
                == recs["overlap_off"]["wire_buckets"])
        # the retired rung stayed retired (decision rule, ISSUE 8):
        # db's bench presence ended when the overlap engine landed
        sys.path.insert(0, os.path.join(repo, "benchmarks"))
        try:
            import comm_overlap_bench as _cob

            names = set(_cob._variants())
        finally:
            sys.path.pop(0)
        assert "wire_db_on" not in names
        assert {"overlap_off", "overlap_on", "overlap_resnet_off",
                "overlap_resnet_on"} <= names


# ----------------------------------------------------------------------
# satellite: pipelined eager tiers == serial, bit for bit
# ----------------------------------------------------------------------
class TestEagerPipelining:
    def _grads(self, size, seed=0):
        rng = np.random.RandomState(seed)
        return {
            "a": jnp.asarray(rng.randn(size, 6, 4), jnp.float32),
            "b": jnp.asarray(rng.randn(size, 31), jnp.float32),
            "c": jnp.asarray(rng.randn(size, 5), jnp.bfloat16),
        }

    @staticmethod
    def _serial_reference(comm, grads, mean=True):
        """The pre-pipelining serial schedule, verbatim: pack, reduce
        bucket k fully, ship it, only then touch bucket k+1 — the
        arithmetic the pipelined path must reproduce bit for bit."""
        dt = comm.allreduce_grad_dtype
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        hosts = [np.asarray(jax.device_get(g)) for g in leaves]
        size = comm.size
        plan = cw.make_plan([h[0] for h in hosts])
        placed = []
        for cat in cw.pack_stacked(plan, hosts, size, xp=np):
            if dt is None:
                red = cat.mean(axis=0) if mean else cat.sum(axis=0)
            else:
                red = np.sum(cat.astype(dt), axis=0, dtype=dt)
                red = red.astype(cat.dtype)
                if mean:
                    red = red / size
            placed.append(jnp.asarray(
                np.broadcast_to(red, cat.shape).copy()
            ))
        out = cw.unpack_stacked(
            plan, placed, [h.shape for h in hosts]
        )
        return jax.tree_util.tree_unflatten(treedef, out)

    @pytest.mark.parametrize("dtype", [None, "bfloat16"])
    @pytest.mark.parametrize("mean", [True, False])
    def test_host_staged_pipelined_equals_serial(self, devices8, dtype,
                                                 mean):
        """Satellite acceptance: the ThreadPool-pipelined host-staged
        bucket exchange (bucket k+1's reduce overlapping bucket k's
        device_put) returns EXACTLY the serial schedule's result — per
        bucket the arithmetic and order are unchanged."""
        comm = cmn.create_communicator(
            "non_cuda_aware", devices=devices8,
            allreduce_grad_dtype=dtype,
        )
        grads = self._grads(comm.size)
        out = comm.allreduce_grad(grads, mean=mean)
        ref = self._serial_reference(comm, grads, mean=mean)
        _assert_tree_bit_equal(
            jax.tree_util.tree_map(lambda x: np.asarray(x), out), ref
        )

    def test_xla_eager_staged_dispatch_matches_oracle(self, devices8):
        """All-buckets-staged-then-reduced dispatch (the pipelined
        order) returns the same means as the numpy oracle."""
        comm = cmn.create_communicator("tpu", devices=devices8)
        grads = self._grads(comm.size, seed=7)
        out = comm.allreduce_grad(grads, mean=True)
        for k, g in grads.items():
            np.testing.assert_allclose(
                np.asarray(out[k][0], np.float32),
                np.asarray(jax.device_get(g), np.float32).mean(axis=0),
                rtol=2e-2, atol=1e-2,
            )

"""Expert-parallel MoE tests.

The reference's EP story is "alltoall is the primitive it would need"
(SURVEY.md section 2, parallelism table); these tests pin the realized
capability: routing bookkeeping, all_to_all dispatch/combine numerics
vs a dense oracle, differentiability, and capacity-drop semantics.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from chainermn_tpu.parallel.expert_parallel import (
    compute_capacity,
    expert_parallel_moe,
    mlp_experts,
    top_k_routing,
)

E = 8  # experts == mesh size: one expert per chip
D, H = 16, 32
T_LOCAL = 16  # tokens per shard


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(E * T_LOCAL, D), jnp.float32) * 0.5
    rw = jnp.asarray(rng.randn(D, E), jnp.float32) * 0.3
    w1 = jnp.asarray(rng.randn(E, D, H), jnp.float32) * 0.2
    w2 = jnp.asarray(rng.randn(E, H, D), jnp.float32) * 0.2
    return x, rw, w1, w2


def _dense_oracle(x, rw, w1, w2, k=2):
    """Per-token direct evaluation: top-k experts, renormalized gates."""
    probs = jax.nn.softmax(x @ rw, axis=-1)
    out = np.zeros_like(np.asarray(x))
    probs_np = np.asarray(probs)
    for t in range(x.shape[0]):
        top = np.argsort(-probs_np[t])[:k]
        denom = probs_np[t][top].sum() if k > 1 else 1.0
        for e in top:
            h = np.asarray(jax.nn.gelu(np.asarray(x[t]) @ np.asarray(w1[e])))
            y = h @ np.asarray(w2[e])
            g = probs_np[t][e] / (denom + 1e-9) if k > 1 else probs_np[t][e]
            out[t] += g * y
    return out


class TestRouting:
    def test_capacity_formula(self):
        assert compute_capacity(128, 8, 2, 1.0) == 32
        assert compute_capacity(1, 64, 1, 1.0) == 1  # never zero

    @pytest.mark.parametrize("k", [1, 2])
    def test_dispatch_within_capacity_and_k_routes(self, k):
        rng = np.random.RandomState(1)
        probs = jax.nn.softmax(
            jnp.asarray(rng.randn(24, E), jnp.float32), -1
        )
        cap = 5
        dispatch, combine, raw = top_k_routing(probs, k, cap)
        # raw routes: exactly k per token, regardless of capacity
        np.testing.assert_allclose(np.asarray(raw).sum(axis=-1), k)
        d = np.asarray(dispatch)
        # each expert slot used at most once
        assert (d.sum(axis=0) <= 1.0 + 1e-6).all()
        # each token dispatched to at most k (expert, slot) pairs
        assert (d.sum(axis=(1, 2)) <= k + 1e-6).all()
        # combine weights only where dispatched, and <= prob
        c = np.asarray(combine)
        assert ((c > 0) <= (d > 0)).all()

    def test_combine_gates_renormalized_top2(self):
        probs = jnp.asarray([[0.6, 0.3, 0.1, 0.0, 0.0, 0.0, 0.0, 0.0]],
                            jnp.float32)
        _, combine, _ = top_k_routing(probs, 2, 4)
        got = np.asarray(combine).sum()
        np.testing.assert_allclose(got, 1.0, atol=1e-5)  # 0.6/0.9 + 0.3/0.9

    def test_underflowed_row_does_not_reroute_same_expert(self):
        # Row where every prob but one underflows to exactly 0.0: route 2
        # must NOT re-pick the route-1 expert (zero-masking bug).
        probs = jnp.zeros((1, E), jnp.float32).at[0, 3].set(1.0)
        dispatch, _, raw = top_k_routing(probs, 2, 4)
        assert float(np.asarray(raw)[0, 3]) == 1.0  # picked exactly once
        assert np.asarray(dispatch)[0, 3].sum() <= 1.0 + 1e-6

    def test_k_exceeding_experts_rejected(self):
        probs = jnp.full((4, E), 1.0 / E, jnp.float32)
        with pytest.raises(ValueError, match="cannot exceed"):
            top_k_routing(probs, E + 1, 4)

    def test_aux_loss_penalizes_collapse_even_with_drops(self):
        from chainermn_tpu.parallel.expert_parallel import (
            load_balancing_loss,
        )

        t = 16
        collapsed = jnp.zeros((t, E), jnp.float32).at[:, 0].set(1.0)
        uniform = jnp.full((t, E), 1.0 / E, jnp.float32)
        cap = 1  # nearly everything at the collapsed expert is dropped
        _, _, raw_c = top_k_routing(collapsed, 1, cap)
        _, _, raw_u = top_k_routing(uniform, 1, cap)
        aux_c = float(load_balancing_loss(collapsed, raw_c))
        aux_u = float(load_balancing_loss(uniform, raw_u))
        assert aux_c > aux_u  # collapse must score WORSE despite drops


class TestExpertParallelMoE:
    @pytest.mark.parametrize("impl", ["einsum", "scatter", "gather"])
    @pytest.mark.parametrize("k", [1, 2])
    def test_matches_dense_oracle_when_no_drops(self, mesh8, k, impl):
        x, rw, w1, w2 = _problem()
        oracle = _dense_oracle(x, rw, w1, w2, k=k)

        f = jax.jit(
            jax.shard_map(
                lambda x, rw, w1, w2: expert_parallel_moe(
                    x, rw, mlp_experts(w1, w2), "mn", E, k=k,
                    capacity=T_LOCAL,  # roomy: no token dropped
                    dispatch_impl=impl,
                ),
                mesh=mesh8,
                in_specs=(P("mn"), P(), P("mn"), P("mn")),
                out_specs=(P("mn"), P()),
                check_vma=False,
            )
        )
        xs = jax.device_put(x, NamedSharding(mesh8, P("mn")))
        y, aux = f(xs, rw, w1, w2)
        np.testing.assert_allclose(
            np.asarray(y), oracle, rtol=2e-4, atol=2e-5
        )
        assert float(aux) > 0.0

    def test_scatter_matches_einsum_with_drops_and_grads(self, mesh8):
        """The dispatch backends are numerically interchangeable —
        including dropped routes (tight capacity) and gradients through
        gates, router, and expert weights."""
        x, rw, w1, w2 = _problem(seed=7)
        results = {}
        for impl in ("einsum", "scatter", "gather"):
            def loss(x, rw, w1, w2, impl=impl):
                y, aux = expert_parallel_moe(
                    x, rw, mlp_experts(w1, w2), "mn", E, k=2,
                    capacity=3,  # tight: real drops
                    dispatch_impl=impl,
                )
                return lax.pmean(jnp.sum(y**2), "mn") + 0.01 * aux

            fwd = jax.jit(
                jax.shard_map(
                    lambda x, rw, w1, w2, impl=impl: expert_parallel_moe(
                        x, rw, mlp_experts(w1, w2), "mn", E, k=2,
                        capacity=3, dispatch_impl=impl,
                    )[0],
                    mesh=mesh8,
                    in_specs=(P("mn"), P(), P("mn"), P("mn")),
                    out_specs=P("mn"), check_vma=False,
                )
            )
            grad = jax.jit(
                jax.shard_map(
                    jax.grad(loss, argnums=(1, 2, 3)), mesh=mesh8,
                    in_specs=(P("mn"), P(), P("mn"), P("mn")),
                    out_specs=(P(), P("mn"), P("mn")), check_vma=False,
                )
            )
            xs = jax.device_put(x, NamedSharding(mesh8, P("mn")))
            results[impl] = (
                np.asarray(fwd(xs, rw, w1, w2)),
                [np.asarray(g) for g in grad(xs, rw, w1, w2)],
            )
        for other in ("scatter", "gather"):
            np.testing.assert_allclose(
                results[other][0], results["einsum"][0],
                rtol=1e-5, atol=1e-6,
            )
            for gs, ge in zip(results[other][1], results["einsum"][1]):
                np.testing.assert_allclose(gs, ge, rtol=1e-4, atol=1e-6)

    def test_differentiable_through_router_and_experts(self, mesh8):
        x, rw, w1, w2 = _problem(seed=3)

        def loss(x, rw, w1, w2):
            y, aux = expert_parallel_moe(
                x, rw, mlp_experts(w1, w2), "mn", E, k=2,
                capacity=T_LOCAL,
            )
            return lax.pmean(jnp.sum(y**2), "mn") + 0.01 * aux

        g = jax.jit(
            jax.shard_map(
                jax.grad(loss, argnums=(1, 2)), mesh=mesh8,
                in_specs=(P("mn"), P(), P("mn"), P("mn")),
                out_specs=(P(), P("mn")),
                check_vma=False,
            )
        )
        xs = jax.device_put(x, NamedSharding(mesh8, P("mn")))
        g_rw, g_w1 = g(xs, rw, w1, w2)
        assert np.isfinite(np.asarray(g_rw)).all()
        assert np.isfinite(np.asarray(g_w1)).all()
        assert np.abs(np.asarray(g_w1)).max() > 0

    def test_capacity_drop_zeroes_overflow_not_nan(self, mesh8):
        x, rw, w1, w2 = _problem(seed=4)
        f = jax.jit(
            jax.shard_map(
                lambda x, rw, w1, w2: expert_parallel_moe(
                    x, rw, mlp_experts(w1, w2), "mn", E, k=1, capacity=1,
                ),
                mesh=mesh8,
                in_specs=(P("mn"), P(), P("mn"), P("mn")),
                out_specs=(P("mn"), P()),
                check_vma=False,
            )
        )
        xs = jax.device_put(x, NamedSharding(mesh8, P("mn")))
        y, _ = f(xs, rw, w1, w2)
        y = np.asarray(y)
        assert np.isfinite(y).all()
        # With 16 tokens/shard, 8 experts, capacity 1: most rows dropped
        zero_rows = (np.abs(y).max(axis=-1) == 0).sum()
        assert zero_rows >= y.shape[0] // 2

    def test_num_experts_divisibility_enforced(self, mesh8):
        x, rw, w1, w2 = _problem()
        f = jax.shard_map(
            lambda x: expert_parallel_moe(
                x, rw, mlp_experts(w1, w2), "mn", 12,
            ),
            mesh=mesh8, in_specs=(P("mn"),), out_specs=(P("mn"), P()),
            check_vma=False,
        )
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(f)(jax.device_put(x, NamedSharding(mesh8, P("mn"))))

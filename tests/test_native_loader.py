"""Native (C++) input-pipeline tests.

SURVEY.md section 2, native-code obligations: csrc/loader.cpp replaces the
reference's MultiprocessIterator + pinned staging path.  The contract
pinned here: batch order and augmentation are deterministic in the seed
for ANY worker-thread count, normalization matches the numpy oracle, and
the epoch bookkeeping mirrors SerialIterator.
"""

import numpy as np
import pytest

from chainermn_tpu.utils.native_loader import (
    NativeImageLoader,
    native_available,
    NativeTokenLoader,
)

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain for the native loader"
)

N, H, W, C = 64, 12, 10, 3
BATCH = 8


def _data(seed=0):
    rng = np.random.RandomState(seed)
    images = rng.randint(0, 256, size=(N, H, W, C), dtype=np.uint8)
    labels = rng.randint(0, 10, size=(N,)).astype(np.int32)
    return images, labels


def _take(loader, k):
    return [next(loader) for _ in range(k)]


class TestEvalModeOracle:
    def test_matches_numpy_center_crop_normalize(self):
        images, labels = _data()
        mean, std = (10.0, 20.0, 30.0), (50.0, 60.0, 70.0)
        crop = (8, 6)
        loader = NativeImageLoader(
            images, labels, BATCH, crop=crop, n_threads=2, seed=7,
            shuffle=False, train=False, mean=mean, std=std,
        )
        x, y = next(loader)
        assert x.shape == (BATCH, 8, 6, C) and x.dtype == np.float32
        off_h, off_w = (H - 8) // 2, (W - 6) // 2
        want = (images[:BATCH, off_h:off_h + 8, off_w:off_w + 6].astype(
            np.float32
        ) - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
        np.testing.assert_allclose(x, want, rtol=1e-6)
        np.testing.assert_array_equal(y, labels[:BATCH])
        loader.close()


class TestDeterminism:
    def _seq(self, n_threads, seed=3, train=True, k=16):
        images, labels = _data()
        loader = NativeImageLoader(
            images, labels, BATCH, crop=(8, 8), n_threads=n_threads,
            seed=seed, shuffle=True, train=train,
        )
        out = _take(loader, k)
        loader.close()
        return out

    def test_thread_count_does_not_change_results(self):
        a = self._seq(n_threads=1)
        b = self._seq(n_threads=4)
        for (xa, ya), (xb, yb) in zip(a, b):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)

    def test_seed_changes_shuffle_and_augmentation(self):
        a = self._seq(n_threads=2, seed=3)
        b = self._seq(n_threads=2, seed=4)
        assert any(
            not np.array_equal(ya, yb) for (_, ya), (_, yb) in zip(a, b)
        )

    def test_epochs_reshuffle(self):
        images, labels = _data()
        loader = NativeImageLoader(
            images, labels, BATCH, n_threads=2, seed=1, shuffle=True,
            train=False,
        )
        bpe = loader.batches_per_epoch
        epoch0 = [y.copy() for _, y in _take(loader, bpe)]
        epoch1 = [y.copy() for _, y in _take(loader, bpe)]
        loader.close()
        # Same multiset of labels each epoch, different order.
        np.testing.assert_array_equal(
            np.sort(np.concatenate(epoch0)), np.sort(np.concatenate(epoch1))
        )
        assert any(
            not np.array_equal(a, b) for a, b in zip(epoch0, epoch1)
        )


class TestBookkeepingAndLifecycle:
    def test_epoch_counters(self):
        images, labels = _data()
        loader = NativeImageLoader(images, labels, BATCH, n_threads=2)
        bpe = loader.batches_per_epoch
        assert bpe == N // BATCH
        assert loader.epoch == 0
        _take(loader, bpe)
        assert loader.epoch == 1
        assert loader.epoch_detail == pytest.approx(1.0)
        loader.close()

    def test_zero_copy_acquire_release(self):
        images, labels = _data()
        loader = NativeImageLoader(
            images, labels, BATCH, n_threads=2, ring=2,
            shuffle=False, train=False,
        )
        slot, x, y = loader.acquire()
        first = x.copy()
        loader.release(slot)
        # After release+reuse the *contents* advance batch by batch.
        for _ in range(loader.batches_per_epoch - 1):
            s2, x2, _ = loader.acquire()
            loader.release(s2)
        np.testing.assert_array_equal(first[0], next(loader)[0][0])
        loader.close()

    def test_bad_config_rejected(self):
        images, labels = _data()
        with pytest.raises(ValueError):
            NativeImageLoader(images, labels, N + 1)  # batch > n
        with pytest.raises(ValueError):
            NativeImageLoader(images, labels, BATCH, crop=(H + 1, W))

    def test_tiny_epoch_ring_spans_stay_deterministic(self):
        # Regression: with batches_per_epoch (2) far below the requested
        # ring (8), tickets from 3+ epochs could race the epoch-parity
        # permutation cache (duplicated/corrupt samples).  The ring is now
        # clamped to one epoch; many epochs must match the 1-thread run.
        rng = np.random.RandomState(0)
        images = rng.randint(0, 256, size=(6, 4, 4, 1), dtype=np.uint8)
        labels = np.arange(6, dtype=np.int32)

        def run(n_threads):
            loader = NativeImageLoader(
                images, labels, 3, n_threads=n_threads, ring=8, seed=5,
                shuffle=True, train=True,
            )
            out = [(x.copy(), y.copy()) for x, y in
                   (next(loader) for _ in range(40))]
            loader.close()
            return out

        ref, par = run(1), run(4)
        for (xa, ya), (xb, yb) in zip(ref, par):
            np.testing.assert_array_equal(ya, yb)
            np.testing.assert_array_equal(xa, xb)
        # No duplicate samples within any epoch (2 batches x 3 = all 6)
        for e in range(20):
            ys = np.concatenate([par[2 * e][1], par[2 * e + 1][1]])
            assert len(set(ys.tolist())) == 6

    def test_serialize_restore_repositions_stream(self):
        images, labels = _data()
        mk = lambda: NativeImageLoader(
            images, labels, BATCH, crop=(8, 8), n_threads=2, seed=9,
            shuffle=True, train=True,
        )
        a = mk()
        _take(a, 5)
        state = a.serialize()
        want = _take(a, 3)
        # Fresh loader, restore, stream must continue identically.
        b = mk()
        _take(b, 11)  # past the snapshot: forces the rewind path
        b.restore(state)
        got = _take(b, 3)
        for (xa, ya), (xb, yb) in zip(want, got):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)
        a.close(), b.close()

    def test_seek_deep_is_constant_time(self):
        # The native seek repositions worker tickets directly: restoring
        # deep into training must NOT produce/discard the skipped batches.
        import time

        images, labels = _data()
        loader = NativeImageLoader(
            images, labels, BATCH, crop=(8, 8), n_threads=2, seed=9,
            shuffle=True, train=True,
        )
        deep = 200_000  # ~25k epochs of 8 batches; replay would take minutes
        t0 = time.monotonic()
        loader.restore({"iteration": deep})
        dt = time.monotonic() - t0
        assert dt < 5.0, f"seek took {dt:.1f}s — looks like a replay"
        assert loader.serialize()["iteration"] == deep
        got = next(loader)
        # Oracle: a fresh loader seeked (not replayed) to the same ticket
        # must produce the identical batch; also check epoch bookkeeping.
        other = NativeImageLoader(
            images, labels, BATCH, crop=(8, 8), n_threads=4, seed=9,
            shuffle=True, train=True,
        )
        other.restore({"iteration": deep})
        want = next(other)
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])
        assert loader.epoch == deep // loader.batches_per_epoch
        loader.close(), other.close()

    def test_restore_refuses_while_slot_held(self):
        # restore's native seek restarts workers, which would overwrite a
        # still-held zero-copy view — it must raise until release()
        images, labels = _data()
        loader = NativeImageLoader(
            images, labels, BATCH, crop=(8, 8), n_threads=2, seed=3,
        )
        state = loader.serialize()
        slot, _x, _y = loader.acquire()
        with pytest.raises(RuntimeError, match="acquired slot"):
            loader.restore(state)
        loader.release(slot)
        loader.restore(state)  # released: seek proceeds
        loader.close()

    def test_train_augmentation_in_range(self):
        images, labels = _data()
        loader = NativeImageLoader(
            images, labels, BATCH, crop=(8, 8), n_threads=3, train=True,
        )
        x, _ = next(loader)
        assert np.isfinite(x).all()
        assert x.min() >= 0.0 and x.max() <= 1.0  # default mean 0, std 255
        loader.close()


class TestUint8Wire:
    """The uint8 wire mode (VERDICT r4 #2): crop/flip in C++, normalize
    on device — half of bf16's bytes over the link.  The contract pinned
    here: identical augmentation geometry to the float32 wire for the
    same seed, and device_normalize(uint8 batch) equals the float32
    wire's host-normalized output exactly (both are fp32 (px-mean)/std,
    one computed in C++, one in XLA)."""

    def test_u8_view_dtype_and_bytes(self):
        images, labels = _data()
        loader = NativeImageLoader(
            images, labels, BATCH, crop=(8, 8), n_threads=2, seed=3,
            wire="uint8",
        )
        slot, x, y = loader.acquire()
        assert x.dtype == np.uint8 and x.shape == (BATCH, 8, 8, C)
        assert x.nbytes == BATCH * 8 * 8 * C  # one byte per pixel-channel
        assert loader.wire == "uint8"
        loader.release(slot)
        loader.close()
        # the 1/4-of-float32 wire claim, against a real float32 batch
        f = NativeImageLoader(
            images, labels, BATCH, crop=(8, 8), n_threads=2, seed=3,
        )
        slot_f, x_f, _y_f = f.acquire()
        assert x_f.dtype == np.float32
        assert x_f.nbytes == 4 * x.nbytes
        f.release(slot_f)
        f.close()

    @pytest.mark.parametrize("train", [False, True])
    def test_matches_float_wire_after_device_normalize(self, train):
        from chainermn_tpu.utils.native_loader import device_normalize

        images, labels = _data()
        mean, std = (10.0, 20.0, 30.0), (50.0, 60.0, 70.0)
        kw = dict(crop=(8, 6), n_threads=2, seed=11, shuffle=True,
                  train=train, mean=mean, std=std)
        f = NativeImageLoader(images, labels, BATCH, **kw)
        u = NativeImageLoader(images, labels, BATCH, wire="uint8", **kw)
        try:
            for _ in range(6):
                xf, yf = next(f)
                xu, yu = next(u)
                np.testing.assert_array_equal(yf, yu)
                got = np.asarray(
                    device_normalize(jnp_asarray(xu), u.mean, u.std)
                )
                # bit-for-bit: device_normalize subtracts then DIVIDES
                # in fp32, the exact op sequence of the C++ float32 wire
                np.testing.assert_array_equal(got, xf)
        finally:
            f.close()
            u.close()

    def test_u8_thread_determinism(self):
        images, labels = _data()

        def run(n_threads):
            ld = NativeImageLoader(
                images, labels, BATCH, crop=(8, 8), wire="uint8",
                n_threads=n_threads, seed=5, shuffle=True, train=True,
            )
            out = [(x.copy(), y.copy()) for x, y in _take(ld, 12)]
            ld.close()
            return out

        for (xa, ya), (xb, yb) in zip(run(1), run(4)):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)

    def test_bad_wire_rejected(self):
        images, labels = _data()
        with pytest.raises(ValueError, match="wire"):
            NativeImageLoader(images, labels, BATCH, wire="bf16")


def jnp_asarray(x):
    import jax.numpy as jnp

    return jnp.asarray(x)


class TestTokenLoader:
    """The LM-path loader over the shared ring engine: shuffled
    fixed-length windows of a flat token stream."""

    def _corpus(self, n=1024):
        return np.arange(n, dtype=np.int32)

    def test_windows_partition_the_corpus(self):
        # one epoch must visit every window exactly once (batch 4 x
        # seq 8 over 256 tokens = 32 windows = 8 batches/epoch)
        ld = NativeTokenLoader(self._corpus(256), 4, 8, seed=3)
        try:
            assert ld.batches_per_epoch == 8
            seen = []
            for _ in range(ld.batches_per_epoch):
                seen.append(next(ld))
            toks = np.concatenate([b.reshape(-1) for b in seen])
            np.testing.assert_array_equal(
                np.sort(toks), np.arange(256, dtype=np.int32)
            )
            # windows are contiguous runs
            firsts = np.concatenate([b[:, 0] for b in seen])
            assert (firsts % 8 == 0).all()
        finally:
            ld.close()

    def test_thread_count_does_not_change_stream(self):
        ref = NativeTokenLoader(self._corpus(), 4, 16, n_threads=1,
                                seed=7)
        many = NativeTokenLoader(self._corpus(), 4, 16, n_threads=7,
                                 seed=7)
        try:
            for _ in range(20):
                np.testing.assert_array_equal(next(ref), next(many))
        finally:
            ref.close()
            many.close()

    def test_epochs_reshuffle_deterministically(self):
        a = NativeTokenLoader(self._corpus(), 8, 8, seed=1)
        b = NativeTokenLoader(self._corpus(), 8, 8, seed=1)
        try:
            bpe = a.batches_per_epoch
            e0 = [next(a) for _ in range(bpe)]
            e1 = [next(a) for _ in range(bpe)]
            assert any(
                not np.array_equal(x, y) for x, y in zip(e0, e1)
            )  # different epoch order
            for x in e0:
                np.testing.assert_array_equal(x, next(b))  # same seed
        finally:
            a.close()
            b.close()

    def test_serialize_restore_repositions(self):
        ld = NativeTokenLoader(self._corpus(), 4, 16, seed=5)
        try:
            for _ in range(5):
                next(ld)
            state = ld.serialize()
            want = [next(ld) for _ in range(4)]
            for _ in range(3):
                next(ld)
            ld.restore(state)
            for w in want:
                np.testing.assert_array_equal(next(ld), w)
        finally:
            ld.close()

    def test_too_small_corpus_rejected(self):
        with pytest.raises(ValueError, match="cannot fill"):
            NativeTokenLoader(np.arange(16, dtype=np.int32), 4, 8)


class TestLoaderThroughput:
    def test_loader_host_pipeline_rate(self):
        """Native-input evidence (VERDICT r3 #3): measure what the
        loader+host-cast pipeline alone produces at bench shapes
        (128x224x224x3 uint8 -> crop/flip/normalize -> bf16 host cast,
        no device in the loop).  The measured tunnel-link input ceiling
        is ~160 img/s at image-like entropy and varies by run
        (benchmarks/h2d_bench.py; docs/performance.md 'Native-input
        pipeline' has the full table) — on a multi-core host the worker
        threads clear it easily, while on the 1-core bench host the
        pipeline is itself host-bound, which is part of the documented
        story.  Only a sanity floor is asserted here (wall-clock
        throughput assertions don't belong in a unit suite)."""
        import time

        import ml_dtypes

        batch, image = 128, 224
        n_data = 512
        rng = np.random.RandomState(0)
        images = rng.randint(
            0, 256, size=(n_data, image + 8, image + 8, 3), dtype=np.uint8
        )
        labels = rng.randint(0, 1000, size=(n_data,)).astype(np.int32)
        loader = NativeImageLoader(
            images, labels, batch, crop=(image, image), n_threads=8,
            seed=0, shuffle=True, train=True,
            mean=(123.7, 116.3, 103.5), std=(58.4, 57.1, 57.4),
        )
        try:
            # warm the ring
            slot, xv, yv = loader.acquire()
            loader.release(slot)
            k = 12
            t0 = time.perf_counter()
            for _ in range(k):
                slot, xv, yv = loader.acquire()
                # the bench's host-side work: bf16 cast detaching the view
                _ = xv.astype(ml_dtypes.bfloat16)
                loader.release(slot)
            dt = time.perf_counter() - t0
        finally:
            loader.close()
        imgs_per_sec = k * batch / dt
        # Sanity floor only: wall-clock throughput in a unit suite must
        # not fail under CI load.  The *evidence* floor (loader clears
        # the measured ~160 img/s link ceiling on a multi-core host) is
        # a bench concern — run this test body manually or see
        # docs/performance.md "Native-input pipeline" for the measured
        # numbers.
        assert imgs_per_sec > 20, (
            f"loader+cast produced only {imgs_per_sec:.0f} img/s - "
            "the native pipeline is pathologically slow"
        )

"""Link tests.

Parity: ``links_tests/test_batch_normalization.py`` — MultiNodeBatchNorm
must equal single-process large-batch BatchNorm; ``test_n_step_rnn.py``.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import chainermn_tpu as cmn
from chainermn_tpu.links import (
    MultiNodeBatchNormalization,
    create_mnbn_model,
    create_multi_node_n_step_rnn,
)
from chainermn_tpu.links.create_mnbn_model import mnbn_factory


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("tpu", devices=devices8)


class TestMultiNodeBatchNormalization:
    def test_matches_large_batch_bn(self, comm):
        """Sharded MNBN over 8 devices == plain BN over the full batch."""
        C = 6
        x = np.random.RandomState(0).randn(32, C).astype(np.float32)

        mnbn = MultiNodeBatchNormalization(
            size=C, axis_name=comm.axis_names
        )
        variables = mnbn.init(jax.random.PRNGKey(0), jnp.zeros((4, C)))

        def fwd(v, xs):
            y, _ = mnbn.apply(v, xs, mutable=["batch_stats"])
            return y

        sharded = jax.jit(
            jax.shard_map(
                fwd, mesh=comm.mesh,
                in_specs=(P(), P(comm.axis_names)),
                out_specs=P(comm.axis_names),
                check_vma=False,
            )
        )
        xg = jax.device_put(jnp.asarray(x), comm.stack_sharding)
        y_sharded = np.asarray(sharded(variables, xg))

        # Oracle: same normalization over the full batch, no axis reduce.
        bn = MultiNodeBatchNormalization(size=C, axis_name=None)
        y_full = np.asarray(
            bn.apply(variables, jnp.asarray(x), mutable=["batch_stats"])[0]
        )
        np.testing.assert_allclose(y_sharded, y_full, rtol=1e-4, atol=1e-5)

    def test_gradient_flows_through_pmean(self, comm):
        C = 4
        mnbn = MultiNodeBatchNormalization(size=C, axis_name=comm.axis_names)
        v = mnbn.init(jax.random.PRNGKey(0), jnp.zeros((2, C)))

        def loss(v, xs):
            y, _ = mnbn.apply(v, xs, mutable=["batch_stats"])
            return jnp.sum(y**2)

        def per_shard(v, xs):
            l, g = jax.value_and_grad(loss)(v, xs)
            return jax.lax.pmean(l, comm.axis_names), jax.tree_util.tree_map(
                lambda t: jax.lax.pmean(t, comm.axis_names), g
            )

        f = jax.jit(
            jax.shard_map(
                per_shard, mesh=comm.mesh,
                in_specs=(P(), P(comm.axis_names)),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )
        x = jnp.asarray(np.random.RandomState(1).randn(16, C), jnp.float32)
        l, g = f(v, jax.device_put(x, comm.stack_sharding))
        assert np.isfinite(float(l))
        gnorm = sum(
            float(jnp.sum(jnp.abs(t))) for t in jax.tree_util.tree_leaves(g)
        )
        assert np.isfinite(gnorm)

    def test_eval_mode_uses_running_stats(self):
        C = 3
        mnbn = MultiNodeBatchNormalization(size=C, axis_name=None)
        v = mnbn.init(jax.random.PRNGKey(0), jnp.zeros((2, C)))
        x = jnp.asarray(np.random.RandomState(2).randn(5, C), jnp.float32)
        y = mnbn.apply(v, x, use_running_average=True)
        # running stats are (0, 1) at init -> output == scale*x + bias == x
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-5,
                                   atol=1e-5)


class TestMnbnFactory:
    def test_factory_builds_bound_module(self, comm):
        make = mnbn_factory(comm)
        m = make(16)
        assert isinstance(m, MultiNodeBatchNormalization)
        assert m.axis_name == comm.axis_names

    def test_create_mnbn_model_replaces_norm_field(self, comm):
        from chainermn_tpu.models import ResNet18

        model = ResNet18(num_classes=10)
        mn = create_mnbn_model(model, comm)
        m = mn.norm(8)
        assert isinstance(m, MultiNodeBatchNormalization)

    def test_foreign_model_with_batchnorm_field_rejected(self, comm):
        import flax.linen as nn

        class Foreign(nn.Module):
            bn: nn.Module = None

            @nn.compact
            def __call__(self, x):
                return self.bn(x)

        model = Foreign(bn=nn.BatchNorm(use_running_average=False))
        with pytest.raises(TypeError, match="cannot be converted"):
            create_mnbn_model(model, comm)

    def test_foreign_bn_free_model_warns_and_passes_through(self, comm):
        import flax.linen as nn

        class Plain(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4)(x)

        model = Plain()
        with pytest.warns(UserWarning, match="UNsynchronized"):
            out = create_mnbn_model(model, comm)
        assert out is model


class TestNStepRNN:
    def test_forward_shapes_and_state_handoff(self):
        rnn = create_multi_node_n_step_rnn(hidden_size=16, num_layers=2)
        x = jnp.zeros((3, 5, 8))
        v = rnn.init(jax.random.PRNGKey(0), x)
        (h, c), ys = rnn.apply(v, x)
        assert h.shape == (2, 3, 16) and c.shape == (2, 3, 16)
        assert ys.shape == (3, 5, 16)
        # hand-off: feed state back in (as the next pipeline stage would)
        (h2, c2), ys2 = rnn.apply(v, x, (h, c))
        assert ys2.shape == (3, 5, 16)

    def test_recurrence_actually_runs(self):
        rnn = create_multi_node_n_step_rnn(hidden_size=4, num_layers=1)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 6, 3), jnp.float32)
        v = rnn.init(jax.random.PRNGKey(1), x)
        _, ys = rnn.apply(v, x)
        # outputs at different timesteps must differ (state evolves)
        assert not np.allclose(np.asarray(ys[:, 0]), np.asarray(ys[:, -1]))

    def test_factory_routing_takes_effect(self, comm):
        # Regression: rank_in/rank_out used to be `del`-ed decoration.
        from chainermn_tpu.link import MultiNodeChainList, PlacedModule

        placed = create_multi_node_n_step_rnn(
            hidden_size=4, comm=comm, rank_in=0, rank_out=None
        )
        assert isinstance(placed, PlacedModule)
        assert placed.rank_in == 0 and placed.rank_out is None

        chain = MultiNodeChainList(comm)
        chain.add_link(
            create_multi_node_n_step_rnn(
                hidden_size=4, comm=comm, rank_in=None, rank_out=1
            ),
        )
        chain.add_link(placed)
        assert chain._stages[0].rank_out == 1
        assert chain._stages[1].rank_in == 0
        # bare-module behavior unchanged when no routing is declared
        bare = create_multi_node_n_step_rnn(hidden_size=4)
        assert not isinstance(bare, PlacedModule)

"""Extensions + trainer-loop tests.

Parity: ``extensions_tests/test_checkpoint.py`` (snapshot/resume
round-trip), evaluator test, ``test_allreduce_persistent.py``; plus the
trainer loop this framework provides in place of Chainer's.
"""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

import chainermn_tpu as cmn
from chainermn_tpu.extensions.evaluator import Evaluator
from chainermn_tpu.extensions.allreduce_persistent import AllreducePersistent
from chainermn_tpu.iterators import SerialIterator
from chainermn_tpu.iterators.serial_iterator import EpochIterator
from chainermn_tpu.training import Trainer, Updater
from chainermn_tpu.training import extensions as T
from chainermn_tpu.models import MLP
from chainermn_tpu.utils import SyntheticImageDataset


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("tpu", devices=devices8)


def _make_training(comm, n=256, batch=64):
    ds = SyntheticImageDataset(n, shape=(8, 8), n_classes=4, seed=0)
    it = SerialIterator(ds, batch, shuffle=True, seed=1)
    model = MLP(n_units=32, n_out=4, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8)))
    params = comm.bcast_data(params)
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)

    def loss_fn(p, b):
        x, y = b
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
    params, opt_state = step.place(params, opt.init(params))
    return model, it, step, params, opt_state


class TestTrainerLoop:
    def test_loss_decreases(self, comm):
        model, it, step, params, opt_state = _make_training(comm)
        updater = Updater(it, step, params, opt_state)
        trainer = Trainer(updater, stop_trigger=(3, "epoch"))
        log = T.LogReport(comm=comm, filename=None)
        trainer.extend(log, trigger=(1, "epoch"))
        trainer.run()
        losses = [e["loss"] for e in log.log if "loss" in e]
        assert len(losses) >= 2
        assert losses[-1] < losses[0]

    def test_stop_by_iteration(self, comm):
        model, it, step, params, opt_state = _make_training(comm)
        trainer = Trainer(
            Updater(it, step, params, opt_state),
            stop_trigger=(5, "iteration"),
        )
        trainer.run()
        assert trainer.iteration == 5

    def test_prefetched_batches_not_replaced(self, comm):
        """Feeding the Updater prefetch_to_device output (already-placed
        global jax.Arrays) must NOT go through place_batch again — in
        multi-process runs re-placing a non-fully-addressable global
        array crashes.  The guard: placed batches pass straight through."""
        from chainermn_tpu.iterators import prefetch_to_device

        model, it, step, params, opt_state = _make_training(comm)
        calls = {"n": 0}
        real_place = step.place_batch

        def counting_place(batch):
            calls["n"] += 1
            return real_place(batch)

        step.place_batch = counting_place
        feed = prefetch_to_device(it, real_place, depth=2)
        trainer = Trainer(
            Updater(feed, step, params, opt_state),
            stop_trigger=(3, "iteration"),
        )
        trainer.run()
        assert trainer.iteration == 3
        # the prefetcher placed them; the Updater must not re-place
        assert calls["n"] == 0


class TestEvaluator:
    def test_global_metrics(self, comm):
        model, it, step, params, opt_state = _make_training(comm)
        ds = SyntheticImageDataset(128, shape=(8, 8), n_classes=4, seed=9)

        def metric_fn(p, b):
            x, y = b
            logits = model.apply(p, x)
            return {
                "accuracy": (jnp.argmax(logits, -1) == y).mean(),
            }

        ev = Evaluator(lambda: EpochIterator(ds, 64), metric_fn, comm)
        out = ev.evaluate(params)
        assert "val/accuracy" in out
        assert 0.0 <= out["val/accuracy"] <= 1.0

    def test_create_multi_node_evaluator_passthrough(self, comm):
        model, it, step, params, opt_state = _make_training(comm)
        ev = Evaluator(lambda: iter(()), lambda p, b: {}, comm)
        assert cmn.create_multi_node_evaluator(ev, comm) is ev

    def test_wrap_foreign_evaluator(self, comm):
        class Plain:
            def evaluate(self):
                return {"loss": 2.0}

        wrapped = cmn.create_multi_node_evaluator(Plain(), comm)
        assert wrapped.evaluate() == {"loss": 2.0}


class TestCheckpointer:
    def test_save_resume_roundtrip(self, comm, tmp_path):
        ckpt = cmn.create_multi_node_checkpointer(
            "t1", comm, path=str(tmp_path)
        )
        state = {
            "params": {"w": jnp.arange(4.0)},
            "step_meta": {"iteration": 7},
        }
        ckpt.save(7, state)
        step, restored = ckpt.resume(like=state)
        assert step == 7
        np.testing.assert_allclose(
            np.asarray(restored["params"]["w"]), np.arange(4.0)
        )

    def test_newest_common_step_and_gc(self, comm, tmp_path):
        ckpt = cmn.create_multi_node_checkpointer(
            "t2", comm, path=str(tmp_path), keep=2
        )
        for s in (1, 2, 3):
            ckpt.save(s, {"x": jnp.zeros(2)})
        assert ckpt.newest_common_step() == 3
        assert len(ckpt._available_steps()) == 2  # GC kept last 2

    def test_resume_empty_returns_none(self, comm, tmp_path):
        ckpt = cmn.create_multi_node_checkpointer(
            "t3", comm, path=str(tmp_path)
        )
        assert ckpt.resume() == (None, None)

    def test_npz_fallback_roundtrips_tree_structure(self, comm, tmp_path,
                                                    monkeypatch):
        # Force the degraded (orbax-less) backend and verify resume()
        # returns the original nested structure — the restore_trainer
        # contract — not a flattened dict.
        ckpt = cmn.create_multi_node_checkpointer(
            "t4", comm, path=str(tmp_path)
        )

        class BrokenOrbax:
            def save(self, *a, **kw):
                raise OSError("orbax unavailable")

        monkeypatch.setattr(ckpt, "_orbax", lambda: BrokenOrbax())
        state = {
            "params": {"w": jnp.arange(4.0), "b": jnp.ones((2,))},
            "opt_state": (jnp.zeros((3,)), {"count": jnp.asarray(5)}),
            "trainer": {"iteration": 7, "epoch": 1},
        }
        ckpt.save(7, state)
        step, restored = ckpt.resume(like=state)
        assert step == 7
        np.testing.assert_allclose(
            np.asarray(restored["params"]["w"]), np.arange(4.0)
        )
        np.testing.assert_allclose(
            np.asarray(restored["opt_state"][0]), np.zeros((3,))
        )
        assert int(restored["opt_state"][1]["count"]) == 5
        assert int(restored["trainer"]["iteration"]) == 7

    def test_async_save_resume_equality(self, comm, tmp_path):
        """The async tier (VERDICT r4 #5): save() returns before the
        write commits; wait_until_finished/resume must still observe a
        complete, byte-equal snapshot — including SHARDED leaves (a
        ZeRO-style 1/N layout restored via the template)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        ckpt = cmn.create_multi_node_checkpointer(
            "t_async", comm, path=str(tmp_path), use_async=True
        )
        sharded = jax.device_put(
            jnp.arange(comm.size * 4.0).reshape(comm.size, 4),
            NamedSharding(comm.mesh, P(comm.axis_names)),
        )
        state = {
            "params": {"w": jnp.arange(4.0), "shard": sharded},
            "opt_state": (jnp.ones((3,)), {"count": jnp.asarray(5)}),
        }
        ckpt.save(3, state)
        # an in-flight save is not yet visible to the directory scan...
        ckpt.wait_until_finished()
        # ...but counts after the drain; resume() drains internally too
        assert ckpt.newest_common_step() == 3
        step, restored = ckpt.resume(like=state)
        assert step == 3
        np.testing.assert_allclose(
            np.asarray(restored["params"]["w"]), np.arange(4.0)
        )
        np.testing.assert_allclose(
            np.asarray(restored["params"]["shard"]), np.asarray(sharded)
        )
        # the sharded leaf must come back SHARDED (template layout),
        # not host-replicated
        assert restored["params"]["shard"].sharding.is_equivalent_to(
            sharded.sharding, sharded.ndim
        )
        assert int(restored["opt_state"][1]["count"]) == 5

    def test_async_requires_orbax(self, comm, tmp_path):
        """use_async with the synchronous npz backend would silently
        break the non-stalling-save contract — rejected loudly."""
        with pytest.raises(ValueError, match="use_async"):
            cmn.create_multi_node_checkpointer(
                "t_bad", comm, path=str(tmp_path),
                use_orbax=False, use_async=True,
            )

    def test_async_back_to_back_saves_serialize(self, comm, tmp_path):
        """Two async saves in a row: the second must wait for the
        first's commit (directory mutations would otherwise race), and
        both snapshots must be resumable."""
        ckpt = cmn.create_multi_node_checkpointer(
            "t_async2", comm, path=str(tmp_path), use_async=True, keep=3
        )
        for s in (1, 2):
            ckpt.save(s, {"x": jnp.full((2,), float(s))})
        step, restored = ckpt.resume()
        assert step == 2
        np.testing.assert_allclose(np.asarray(restored["x"]), 2.0)

    def test_npz_fallback_explicit(self, comm, tmp_path):
        ckpt = cmn.create_multi_node_checkpointer(
            "t5", comm, path=str(tmp_path), use_orbax=False
        )
        state = {"params": {"w": jnp.full((2, 2), 3.0)}, "meta": [1, 2]}
        ckpt.save(1, state)
        step, restored = ckpt.resume()
        assert step == 1
        np.testing.assert_allclose(np.asarray(restored["params"]["w"]), 3.0)
        assert list(restored["meta"]) == [1, 2]


class TestAllreducePersistent:
    def test_single_controller_identity(self, comm):
        arp = AllreducePersistent(comm)
        stats = {"mean": jnp.arange(3.0)}
        out = arp.reduce(stats)
        np.testing.assert_allclose(np.asarray(out["mean"]), np.arange(3.0))

    def test_stacked_per_rank_stats_averaged_in_mesh(self, comm):
        # Eager tier: BN running stats are stacked per-rank; reduce must
        # make every rank's slice the mean over ranks (the reference's
        # allreduce of persistent arrays), via the XLA allreduce.
        arp = AllreducePersistent(comm, stacked=True)
        per_rank = jnp.stack(
            [jnp.full((3,), float(r)) for r in range(comm.size)]
        )
        out = arp.reduce({"running_mean": per_rank})["running_mean"]
        want = np.full((comm.size, 3), np.mean(np.arange(comm.size)))
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)


class TestGlobalExceptHook:
    def test_install_remove(self):
        import sys

        from chainermn_tpu import global_except_hook as geh

        old = sys.excepthook
        geh.add_hook()
        assert sys.excepthook is not old
        geh.remove_hook()
        assert sys.excepthook is sys.__excepthook__


class TestProfileExtension:
    def test_trace_window_produces_profile(self, comm, tmp_path):
        model, it, step, params, opt_state = _make_training(comm)
        trainer = Trainer(
            Updater(it, step, params, opt_state),
            stop_trigger=(6, "iteration"),
        )
        logdir = str(tmp_path / "prof")
        prof = T.Profile(start=2, stop=4, logdir=logdir, comm=comm)
        trainer.extend(prof, trigger=(1, "iteration"))
        trainer.run()
        assert prof.done
        # TensorBoard profile-plugin layout: plugins/profile/<run>/...
        plugin_dir = os.path.join(logdir, "plugins", "profile")
        assert os.path.isdir(plugin_dir)
        runs = os.listdir(plugin_dir)
        assert runs, "no profile run captured"
        files = os.listdir(os.path.join(plugin_dir, runs[0]))
        assert any("trace" in f for f in files), files

    def test_finalize_closes_open_trace(self, comm, tmp_path):
        model, it, step, params, opt_state = _make_training(comm)
        trainer = Trainer(
            Updater(it, step, params, opt_state),
            stop_trigger=(3, "iteration"),  # stops inside the window
        )
        prof = T.Profile(start=1, stop=10, logdir=str(tmp_path / "p2"),
                         comm=comm)
        trainer.extend(prof, trigger=(1, "iteration"))
        trainer.run()
        prof.finalize()
        assert prof.done

    def test_invalid_window_rejected(self):
        with pytest.raises(ValueError):
            T.Profile(start=5, stop=5)


class TestThroughputExtension:
    def test_reports_after_warmup(self, comm):
        model, it, step, params, opt_state = _make_training(comm)
        trainer = Trainer(
            Updater(it, step, params, opt_state),
            stop_trigger=(6, "iteration"),
        )
        trainer.extend(T.Throughput(64, comm=comm), trigger=(1, "iteration"))
        trainer.run()
        assert "samples_per_sec" in trainer.observation
        assert trainer.observation["samples_per_sec_per_chip"] > 0

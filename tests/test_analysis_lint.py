"""mnlint repo gate (ISSUE 5 satellite): the repo self-lints in tier-1,
and the rules behave as documented on synthetic files.

Fast by construction: pure AST work, no jax import in the linted path.
"""

import os
import subprocess
import sys
import textwrap

from chainermn_tpu.analysis.lint import (
    SANCTIONED,
    Violation,
    default_targets,
    lint_file,
    repo_root,
    run_lint,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_src(tmp_path, src, name="offender.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    # tmp files live outside the repo: lint relative to tmp_path so
    # sanctioned-prefix matching sees a clean relative name
    return lint_file(str(p), str(tmp_path))


# ----------------------------------------------------------------------
# the gate itself
# ----------------------------------------------------------------------
class TestRepoGate:
    def test_repo_self_lints_clean(self):
        """Acceptance: the repo AST lint runs clean in tier-1.  Every
        raw-collective site is either routed through the audited
        wrappers or inside the sanctioned comm modules; every timed
        bench row carries the min-of-N disclosure (or an explicit
        pragma naming why not)."""
        violations = run_lint()
        assert violations == [], "\n".join(str(v) for v in violations)

    def test_console_entry_exits_zero_on_clean_repo(self):
        """``python -m chainermn_tpu.analysis.lint`` is the CI gate."""
        proc = subprocess.run(
            [sys.executable, "-m", "chainermn_tpu.analysis.lint"],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "clean" in proc.stdout

    def test_console_entry_exits_nonzero_on_violation(self, tmp_path):
        bad = tmp_path / "offender.py"
        bad.write_text("from jax import lax\nlax.psum(1, 'mn')\n")
        proc = subprocess.run(
            [sys.executable, "-m", "chainermn_tpu.analysis.lint",
             str(bad)],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "raw-collective" in proc.stdout

    def test_default_targets_cover_the_surface(self):
        names = {os.path.basename(t) for t in default_targets()}
        assert {"chainermn_tpu", "benchmarks", "examples",
                "bench.py"} <= names
        # tests are deliberately NOT linted: they construct raw
        # collectives on purpose to exercise the analyzer
        assert "tests" not in names
        assert repo_root() == REPO


# ----------------------------------------------------------------------
# rule: raw-collective
# ----------------------------------------------------------------------
class TestRawCollectiveRule:
    def test_lax_attribute_calls_flagged(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from jax import lax
            def f(x):
                return lax.psum(x, 'mn') + lax.pmean(x, 'mn')
        """)
        assert [v.rule for v in vs] == ["raw-collective"] * 2

    def test_jax_lax_dotted_calls_flagged(self, tmp_path):
        vs = _lint_src(tmp_path, """
            import jax
            def f(x):
                return jax.lax.all_gather(x, 'mn', axis=0, tiled=True)
        """)
        assert len(vs) == 1 and vs[0].line == 4

    def test_from_import_smuggling_flagged(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from jax.lax import psum, ppermute
        """)
        assert len(vs) == 1
        assert "smuggles" in vs[0].message

    def test_import_alias_flagged(self, tmp_path):
        """ISSUE 6 satellite: module aliases put raw collectives one
        attribute access away without the ``lax`` spelling the base
        check keys on."""
        vs = _lint_src(tmp_path, """
            import jax.lax as jl
            def f(x):
                return jl.all_gather(x, 'mn', axis=0, tiled=True)
        """)
        assert [v.rule for v in vs] == ["raw-collective"]
        assert vs[0].line == 4

    def test_from_import_alias_flagged(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from jax import lax as L
            def f(x):
                return L.psum_scatter(x, 'mn', scatter_dimension=0)
        """)
        assert [v.rule for v in vs] == ["raw-collective"]

    def test_assignment_alias_flagged(self, tmp_path):
        vs = _lint_src(tmp_path, """
            import jax
            mylax = jax.lax
            def f(x):
                return mylax.psum(x, 'mn')
        """)
        assert [v.rule for v in vs] == ["raw-collective"]

    def test_alias_of_non_lax_module_not_flagged(self, tmp_path):
        vs = _lint_src(tmp_path, """
            import numpy.linalg as jl
            def f(x):
                return jl.psum(x, 'mn')  # not lax: someone else's psum
        """)
        assert vs == []

    def test_extended_collective_names_flagged(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from jax import lax
            def f(x):
                a = lax.pshuffle(x, 'mn', [0])
                b = lax.all_gather_invariant(x, 'mn')
                return a + b
        """)
        assert [v.rule for v in vs] == ["raw-collective"] * 2

    def test_non_collective_lax_ok(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from jax import lax
            def f(x):
                return lax.axis_index('mn') + lax.rsqrt(x) + lax.scan
        """)
        assert vs == []

    def test_wrapper_calls_ok(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from chainermn_tpu.functions import collectives as cc
            def f(x):
                return cc.psum(x, 'mn') + cc.pmean(x, 'mn')
        """)
        assert vs == []

    def test_pragma_allows(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from jax import lax
            def f(x):
                return lax.psum(x, 'mn')  # mnlint: allow(raw-collective)
        """)
        assert vs == []

    def test_pragma_on_preceding_line_allows(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from jax import lax
            def f(x):
                # mnlint: allow(raw-collective)
                return lax.psum(x, 'mn')
        """)
        assert vs == []

    def test_wrong_pragma_rule_does_not_allow(self, tmp_path):
        vs = _lint_src(tmp_path, """
            from jax import lax
            def f(x):
                return lax.psum(x, 'mn')  # mnlint: allow(untimed-row)
        """)
        assert len(vs) == 1

    def test_sanctioned_prefixes_are_the_comm_layer(self):
        assert "chainermn_tpu/comm_wire/" in SANCTIONED
        assert "chainermn_tpu/functions/" in SANCTIONED
        assert "chainermn_tpu/parallel/" in SANCTIONED
        assert "chainermn_tpu/_compat.py" in SANCTIONED
        # models/links/extensions are NOT sanctioned — they must route
        # through the wrappers (fixed in this PR)
        assert not any(p.startswith("chainermn_tpu/models") for p in SANCTIONED)

    def test_sanctioned_file_not_flagged(self):
        # optimizers.py is the compiled-tier sync layer: full of psums,
        # sanctioned by name
        path = os.path.join(REPO, "chainermn_tpu", "optimizers.py")
        assert [v for v in lint_file(path, REPO)
                if v.rule == "raw-collective"] == []

    def test_adaptive_stays_off_the_sanctioned_list(self):
        """ISSUE 15 satellite: the straggler-adaptive policy engine is
        a DECISION layer — its exchanges ride the obj store's audited
        lockstep retry, never raw device collectives — so neither
        ``resilience/adaptive.py`` nor the resilience package may ever
        join the raw-psum sanctioned list, and the module self-lints
        clean (raw-collective AND raw-timing)."""
        assert not any(
            p.startswith("chainermn_tpu/resilience") for p in SANCTIONED
        ), "resilience/ (adaptive.py included) must stay unsanctioned"
        path = os.path.join(
            REPO, "chainermn_tpu", "resilience", "adaptive.py"
        )
        assert lint_file(path, REPO) == []


# ----------------------------------------------------------------------
# rule: untimed-row
# ----------------------------------------------------------------------
class TestUntimedRowRule:
    def test_timed_row_without_protocol_flagged(self, tmp_path):
        vs = _lint_src(tmp_path, """
            import json
            print(json.dumps({"variant": "x", "step_time_ms": 1.2}))
        """, name="bench_x.py")
        assert [v.rule for v in vs] == ["untimed-row"]

    def test_row_with_n_measurements_ok(self, tmp_path):
        vs = _lint_src(tmp_path, """
            import json
            print(json.dumps({
                "step_time_ms": 1.2, "n_measurements": 3,
                "spread_max_over_min": 1.1,
            }))
        """, name="bench_x.py")
        assert vs == []

    def test_double_star_expansion_skipped(self, tmp_path):
        vs = _lint_src(tmp_path, """
            import json
            fields = {"n_measurements": 2}
            print(json.dumps({"step_time_ms": 1.2, **fields}))
        """, name="bench_x.py")
        assert vs == []

    def test_update_arg_skipped(self, tmp_path):
        vs = _lint_src(tmp_path, """
            rec = {"n_measurements": 2}
            rec.update({"extra_ms": 3.4})
        """, name="bench_x.py")
        assert vs == []

    def test_dict_enriched_by_helper_skipped(self, tmp_path):
        vs = _lint_src(tmp_path, """
            import json
            def emit(merge_protocol):
                rec = {"step_time_ms": 1.2}
                merge_protocol(rec)
                print(json.dumps(rec))
        """, name="bench_x.py")
        assert vs == []

    def test_enrichment_in_one_function_does_not_exempt_another(
        self, tmp_path
    ):
        """Regression: name tracking is per actual scope.  Function B's
        enriched ``out`` must not exempt function A's unrelated literal
        of the same name."""
        vs = _lint_src(tmp_path, """
            import json
            def a():
                out = {"step_time_ms": 1.2}
                print(json.dumps(out))
            def b():
                out = {"other": 1}
                enrich(out)
        """, name="bench_x.py")
        assert [v.rule for v in vs] == ["untimed-row"]
        assert vs[0].line == 4

    def test_emission_calls_do_not_exempt(self, tmp_path):
        vs = _lint_src(tmp_path, """
            import json
            def emit():
                rec = {"step_time_ms": 1.2}
                print(json.dumps(rec))
        """, name="bench_x.py")
        assert len(vs) == 1

    def test_rule_only_applies_to_bench_files(self, tmp_path):
        src = """
            row = {"step_time_ms": 1.2}
        """
        assert _lint_src(tmp_path, src, name="bench_y.py") != []
        assert _lint_src(tmp_path, src, name="module.py") == []

    def test_untimed_keys_ok(self, tmp_path):
        vs = _lint_src(tmp_path, """
            cfg = {"batch": 8, "layers": 2, "milestones": [1, 2]}
        """, name="bench_x.py")
        assert vs == []

    def test_violation_formatting(self):
        v = Violation("b.py", 3, "untimed-row", "msg")
        assert str(v) == "b.py:3: [untimed-row] msg"


# ----------------------------------------------------------------------
# rule: raw-timing (ISSUE 10 satellite)
# ----------------------------------------------------------------------
class TestRawTimingRule:
    def _lint_pkg(self, tmp_path, src, rel="chainermn_tpu/mod.py"):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        return lint_file(str(p), str(tmp_path))

    def test_time_time_and_perf_counter_flagged(self, tmp_path):
        vs = self._lint_pkg(tmp_path, """
            import time
            def f():
                return time.time() + time.perf_counter()
        """)
        assert [v.rule for v in vs] == ["raw-timing"] * 2

    def test_monotonic_is_permitted(self, tmp_path):
        vs = self._lint_pkg(tmp_path, """
            import time
            def f():
                return time.monotonic(), time.sleep(0)
        """)
        assert vs == []

    def test_module_alias_tracked(self, tmp_path):
        vs = self._lint_pkg(tmp_path, """
            import time as t
            def f():
                return t.perf_counter()
        """)
        assert [v.rule for v in vs] == ["raw-timing"]

    def test_from_import_smuggling_flagged(self, tmp_path):
        vs = self._lint_pkg(tmp_path, """
            from time import perf_counter as pc
            def f():
                return pc()
        """)
        assert [v.rule for v in vs] == ["raw-timing"]

    def test_sanctioned_timing_modules_exempt(self, tmp_path):
        src = """
            import time
            def f():
                return time.perf_counter()
        """
        assert self._lint_pkg(
            tmp_path, src, rel="chainermn_tpu/observability/timeline.py"
        ) == []
        assert self._lint_pkg(
            tmp_path, src, rel="chainermn_tpu/utils/benchmarking.py"
        ) == []
        # the rule is scoped to the package: bench scripts measure
        # with raw clocks by design
        assert self._lint_pkg(
            tmp_path, src, rel="benchmarks/some_bench.py"
        ) == []

    def test_pragma_escape(self, tmp_path):
        vs = self._lint_pkg(tmp_path, """
            import time
            WALL = time.time()  # mnlint: allow(raw-timing)
        """)
        assert vs == []

    def test_unrelated_attributes_not_flagged(self, tmp_path):
        vs = self._lint_pkg(tmp_path, """
            class Clock:
                def time(self):
                    return 0
            def f(c):
                return c.time()
        """)
        assert vs == []


# ----------------------------------------------------------------------
# host-protocol rules (ISSUE 20): spmd-hash / spmd-unsorted-scan /
# spmd-random, scoped to DECISION_MODULES, behind --host-protocol
# ----------------------------------------------------------------------
class TestSpmdRules:
    def _lint_decision(self, tmp_path, src,
                       name="chainermn_tpu/serving/mod.py"):
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        return lint_file(str(p), str(tmp_path), host_protocol=True)

    def test_builtin_hash_flagged(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            def pick(key, n):
                return hash(key) % n
        """)
        assert [v.rule for v in vs] == ["spmd-hash"]

    def test_hashlib_not_flagged(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import hashlib
            def pick(key, n):
                return int(hashlib.sha256(key).hexdigest(), 16) % n
        """)
        assert vs == []

    def test_unsorted_listdir_iteration_flagged(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import os
            def scan(root):
                for name in os.listdir(root):
                    yield name
        """)
        assert [v.rule for v in vs] == ["spmd-unsorted-scan"]

    def test_tainted_name_iteration_flagged(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import os
            def scan(root):
                names = os.listdir(root)
                return [n for n in names]
        """)
        assert [v.rule for v in vs] == ["spmd-unsorted-scan"]

    def test_glob_alias_and_smuggled_listdir_flagged(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import glob as _glob
            from os import listdir
            def scan(root):
                for p in _glob.glob(root + "/*"):
                    pass
                for n in listdir(root):
                    pass
        """)
        assert [v.rule for v in vs] == ["spmd-unsorted-scan"] * 2

    def test_sorted_scan_is_clean(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import glob, os
            def scan(root):
                for name in sorted(os.listdir(root)):
                    pass
                for p in sorted(glob.glob(root + "/*")):
                    pass
        """)
        assert vs == []

    def test_order_insensitive_reducer_exempts_genexp(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import os
            def scan(root):
                n = len([x for x in os.listdir(root)])
                newest = max(int(x) for x in os.listdir(root))
                every = all(x for x in os.listdir(root))
                return n, newest, every
        """)
        assert vs == []

    def test_set_iteration_flagged(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            def f(items):
                for x in set(items):
                    pass
                for y in {1, 2, 3}:
                    pass
        """)
        assert [v.rule for v in vs] == ["spmd-unsorted-scan"] * 2

    def test_sorted_set_is_clean(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            def f(items):
                for x in sorted(set(items)):
                    pass
        """)
        assert vs == []

    def test_random_module_draws_flagged(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import random
            import numpy as np
            def f(items):
                random.shuffle(items)
                return np.random.randint(10)
        """)
        assert [v.rule for v in vs] == ["spmd-random"] * 2

    def test_smuggled_draw_flagged(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            from random import choice
            def f(items):
                return choice(items)
        """)
        assert [v.rule for v in vs] == ["spmd-random"]

    def test_jax_random_and_seeded_instances_clean(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import jax
            import numpy as np
            def f(seed):
                key = jax.random.PRNGKey(seed)
                key = jax.random.split(key)[0]
                rng = np.random.RandomState(seed)
                gen = np.random.default_rng(seed)
                return key, rng.randn(3), gen.standard_normal(3)
        """)
        assert vs == []

    def test_pragma_escapes_each_rule(self, tmp_path):
        vs = self._lint_decision(tmp_path, """
            import os, random
            def f(root, items, key):
                h = hash(key)  # mnlint: allow(spmd-hash)
                # mnlint: allow(spmd-unsorted-scan)
                for n in os.listdir(root):
                    pass
                random.shuffle(items)  # mnlint: allow(spmd-random)
                return h
        """)
        assert vs == []

    def test_rules_scoped_to_decision_modules(self, tmp_path):
        """The same hazards OUTSIDE a decision module (and anywhere
        with host_protocol off) are not flagged — the rules target
        cross-rank decision surfaces, not all Python."""
        src = """
            import os, random
            def f(root, items, key):
                random.shuffle(items)
                for n in os.listdir(root):
                    pass
                return hash(key)
        """
        vs = self._lint_decision(
            tmp_path, src, name="chainermn_tpu/utils/mod.py"
        )
        assert vs == []
        p = tmp_path / "chainermn_tpu/serving/off.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        assert lint_file(str(p), str(tmp_path)) == []  # flag off

    def test_spmd_allowlist_is_closed_and_empty(self):
        """ISSUE 20 acceptance: serving/ and fleet/ are decision
        modules and sit on NO sanctioned allowlist — not the raw-psum
        one, not the timing one, and the SPMD allowlist itself is
        empty by contract."""
        from chainermn_tpu.analysis.lint import (
            DECISION_MODULES,
            SPMD_ALLOWLIST,
            TIMING_SANCTIONED,
        )

        assert SPMD_ALLOWLIST == ()
        for pkg in ("chainermn_tpu/serving/", "chainermn_tpu/fleet/"):
            assert pkg in DECISION_MODULES
            assert not any(pkg.startswith(p) for p in SANCTIONED)
            assert not any(pkg.startswith(p) for p in TIMING_SANCTIONED)
            assert not any(pkg.startswith(p) for p in SPMD_ALLOWLIST)


class TestHostProtocolGate:
    def test_repo_self_lints_clean_under_host_protocol(self):
        """ISSUE 20 acceptance: the repo passes the FULL rule set —
        the classic rules, the SPMD-determinism rules over every
        decision module, and the protolint catalog rules — in tier-1."""
        violations = run_lint(host_protocol=True)
        assert violations == [], "\n".join(str(v) for v in violations)

    def test_cli_flag_folds_protolint_in(self, tmp_path):
        import subprocess
        import sys

        bad = tmp_path / "offender.py"
        bad.write_text("SHARD_TAG = 4242\n")
        proc = subprocess.run(
            [sys.executable, "-m", "chainermn_tpu.analysis.lint",
             "--host-protocol", str(bad)],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1
        assert "proto-magic-tag" in proc.stdout

    def test_unsorted_listdir_fixture_trips_gate(self, tmp_path):
        """The end-to-end satellite contract: a decision-module file
        iterating a raw listdir fails the gate."""
        p = tmp_path / "chainermn_tpu/fleet/bad.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(
            "import os\n"
            "def pick(root):\n"
            "    return [d for d in os.listdir(root)]\n"
        )
        vs = run_lint([str(tmp_path)], str(tmp_path),
                      host_protocol=True)
        assert [v.rule for v in vs] == ["spmd-unsorted-scan"]

    def test_flag_off_keeps_legacy_behaviour(self, tmp_path):
        p = tmp_path / "chainermn_tpu/fleet/bad.py"
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("import os\nX = [d for d in os.listdir('.')]\n")
        assert run_lint([str(tmp_path)], str(tmp_path)) == []

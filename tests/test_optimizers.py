"""Multi-node optimizer tests.

Parity: ``optimizers_tests/test_multi_node_optimizer.py`` — grads applied
equal the mean of per-rank grads; double-buffering staleness semantics.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

import chainermn_tpu as cmn
from chainermn_tpu.optimizers import build_train_step


@pytest.fixture(scope="module")
def comm(devices8):
    return cmn.create_communicator("tpu", devices=devices8)


def _quadratic_loss(params, batch):
    # loss = 0.5 * ||w - x_mean||^2 per shard; grad = w - mean(local batch)
    x = batch
    return 0.5 * jnp.sum((params["w"] - x.mean(axis=0)) ** 2)


class TestGradientSync:
    def test_update_applies_mean_gradient(self, comm):
        opt = cmn.create_multi_node_optimizer(optax.sgd(1.0), comm)
        params = {"w": jnp.zeros((4,))}
        step = build_train_step(comm, _quadratic_loss, opt, donate=False)
        params, opt_state = step.place(params, opt.init(params))
        # batch: shard r has all-r rows -> local grad = w - r
        x = jnp.stack([jnp.full((4,), float(r)) for r in range(8)])
        bx = jax.device_put(x, step.batch_sharding)
        new_params, _, metrics = step(params, opt_state, bx)
        # mean over ranks of (w - r) = -3.5 ; sgd(1.0): w <- w + 3.5
        np.testing.assert_allclose(np.asarray(new_params["w"]), 3.5, rtol=1e-6)

    def test_loss_is_global_mean(self, comm):
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.0), comm)
        params = {"w": jnp.zeros((4,))}
        step = build_train_step(comm, _quadratic_loss, opt, donate=False)
        params, opt_state = step.place(params, opt.init(params))
        x = jnp.stack([jnp.full((4,), float(r)) for r in range(8)])
        _, _, metrics = step(params, opt_state, jax.device_put(x, step.batch_sharding))
        expect = np.mean([0.5 * 4 * r * r for r in range(8)])
        np.testing.assert_allclose(float(metrics["loss"]), expect, rtol=1e-5)

    def test_gspmd_path_matches_shard_map_path(self, comm):
        opt1 = cmn.create_multi_node_optimizer(optax.sgd(0.5), comm)
        opt2 = optax.sgd(0.5)
        params = {"w": jnp.ones((4,))}
        x = jnp.stack([jnp.full((4,), float(r)) for r in range(8)])

        s1 = build_train_step(comm, _quadratic_loss, opt1, donate=False)
        p1, o1 = s1.place(params, opt1.init(params))
        p1, _, _ = s1(p1, o1, jax.device_put(x, s1.batch_sharding))

        def global_loss(params, batch):
            return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

        s2 = build_train_step(comm, global_loss, opt2, donate=False,
                              use_shard_map=False)
        p2, o2 = s2.place(params, opt2.init(params))
        p2, _, _ = s2(p2, o2, jax.device_put(x, s2.batch_sharding))
        # Note: shard-map path averages per-shard losses of per-shard means;
        # GSPMD path differentiates global-batch mean. For this loss both
        # give w - mean(r) gradients.
        np.testing.assert_allclose(
            np.asarray(p1["w"]), np.asarray(p2["w"]), rtol=1e-5
        )


class TestGradAccumulation:
    """accum_steps=k: microbatched gradients inside one compiled step.
    For a mean-style loss over equal microbatches the numerics match the
    unaccumulated step exactly."""

    def _mean_loss(self, params, batch):
        x = batch
        return jnp.mean((x @ params["w"] - 1.0) ** 2)

    def _run(self, comm, accum, n_steps=3):
        opt = cmn.create_multi_node_optimizer(optax.adam(0.1), comm)
        params = {"w": jnp.ones((4,)) * 0.3}
        step = build_train_step(
            comm, self._mean_loss, opt, donate=False, accum_steps=accum
        )
        params, opt_state = step.place(params, opt.init(params))
        x = jnp.asarray(
            np.random.RandomState(0).randn(32, 4), jnp.float32
        )
        bx = jax.device_put(x, step.batch_sharding)
        losses = []
        for _ in range(n_steps):
            params, opt_state, m = step(params, opt_state, bx)
            losses.append(float(m["loss"]))
        return np.asarray(params["w"]), losses

    def test_matches_unaccumulated(self, comm):
        w1, l1 = self._run(comm, accum=1)
        w2, l2 = self._run(comm, accum=2)
        w4, l4 = self._run(comm, accum=4)
        np.testing.assert_allclose(l2, l1, rtol=1e-5)
        np.testing.assert_allclose(l4, l1, rtol=1e-5)
        np.testing.assert_allclose(w2, w1, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(w4, w1, rtol=1e-5, atol=1e-7)

    def test_indivisible_microbatch_rejected(self, comm):
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        params = {"w": jnp.ones((4,))}
        step = build_train_step(
            comm, self._mean_loss, opt, donate=False, accum_steps=3
        )
        params, opt_state = step.place(params, opt.init(params))
        x = jnp.zeros((32, 4))  # 4 rows/chip, not divisible by 3
        with pytest.raises(ValueError, match="accum_steps"):
            step(params, opt_state, jax.device_put(x, step.batch_sharding))

    def test_bad_accum_steps_rejected(self, comm):
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        with pytest.raises(ValueError, match="accum_steps"):
            build_train_step(comm, self._mean_loss, opt, accum_steps=0)

    def test_with_aux_state(self, comm):
        """has_aux + accumulation: numeric aux leaves are averaged over
        microbatches (and across the mesh)."""

        def loss_fn(params, batch):
            x = batch
            loss = jnp.mean((x @ params["w"]) ** 2)
            return loss, {"batch_mean": jnp.mean(x)}

        opt = cmn.create_multi_node_optimizer(optax.sgd(0.01), comm)
        params = {"w": jnp.ones((4,))}
        step = build_train_step(
            comm, loss_fn, opt, donate=False, accum_steps=2,
            has_aux=True,
            merge_aux=lambda p, a: {"w": p["w"], "seen": a["batch_mean"]},
        )
        full = {"w": params["w"], "seen": jnp.zeros(())}
        params, opt_state = step.place(full, opt.init(full))
        x = jnp.asarray(
            np.random.RandomState(1).randn(32, 4), jnp.float32
        )
        params, opt_state, m = step(
            params, opt_state, jax.device_put(x, step.batch_sharding)
        )
        assert np.isfinite(float(m["loss"]))
        # numeric aux averaged over microbatches AND the mesh = the
        # global batch mean
        np.testing.assert_allclose(
            float(params["seen"]), float(jnp.mean(x)), rtol=1e-5
        )


class TestRemat:
    """remat=True rematerializes the forward in the backward — values
    and updates must be bit-comparable to the plain step."""

    def _mlp_loss(self, params, batch):
        x = batch
        h = jnp.tanh(x @ params["w1"])
        return jnp.mean((h @ params["w2"]) ** 2)

    def _run(self, comm, remat):
        opt = cmn.create_multi_node_optimizer(optax.adam(0.05), comm)
        rng = np.random.RandomState(0)
        params = {
            "w1": jnp.asarray(rng.randn(4, 8), jnp.float32) * 0.4,
            "w2": jnp.asarray(rng.randn(8, 2), jnp.float32) * 0.4,
        }
        step = build_train_step(
            comm, self._mlp_loss, opt, donate=False, remat=remat,
            accum_steps=2,
        )
        params, opt_state = step.place(params, opt.init(params))
        x = jnp.asarray(rng.randn(32, 4), jnp.float32)
        bx = jax.device_put(x, step.batch_sharding)
        for _ in range(3):
            params, opt_state, m = step(params, opt_state, bx)
        return np.asarray(params["w1"]), float(m["loss"])

    def test_remat_matches_plain(self, comm):
        w_plain, l_plain = self._run(comm, remat=False)
        w_remat, l_remat = self._run(comm, remat=True)
        np.testing.assert_allclose(l_remat, l_plain, rtol=1e-6)
        np.testing.assert_allclose(w_remat, w_plain, rtol=1e-6, atol=1e-8)

    def test_policy_object_accepted(self, comm):
        policy = jax.checkpoint_policies.nothing_saveable
        w_pol, l_pol = self._run(comm, remat=policy)
        w_plain, l_plain = self._run(comm, remat=False)
        np.testing.assert_allclose(l_pol, l_plain, rtol=1e-6)
        np.testing.assert_allclose(w_pol, w_plain, rtol=1e-6, atol=1e-8)


class TestDoubleBuffering:
    def test_first_update_is_zero_then_stale(self, comm):
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(1.0), comm, double_buffering=True
        )
        params = {"w": jnp.zeros((2,))}
        step = build_train_step(comm, _quadratic_loss, opt, donate=False)
        params, opt_state = step.place(params, opt.init(params))
        x = jnp.stack([jnp.full((2,), float(r)) for r in range(8)])
        bx = jax.device_put(x, step.batch_sharding)

        p1, opt_state, _ = step(params, opt_state, bx)
        # step 1 applied zeros (no synced grads yet)
        np.testing.assert_allclose(np.asarray(p1["w"]), 0.0, atol=1e-7)
        p2, opt_state, _ = step(p1, opt_state, bx)
        # step 2 applies step-1's grads: mean(w0 - r) = -3.5 -> w = 3.5
        np.testing.assert_allclose(np.asarray(p2["w"]), 3.5, rtol=1e-6)

    def test_state_carries_step_count(self, comm):
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.1), comm, double_buffering=True
        )
        params = {"w": jnp.zeros((2,))}
        state = opt.init(params)
        assert int(state.step) == 0
        assert "prev_grads" in state._fields


class TestReducedPrecisionGrads:
    def test_bf16_grad_sync_close_to_fp32(self, devices8):
        comm_bf16 = cmn.create_communicator(
            "tpu", devices=devices8, allreduce_grad_dtype=jnp.bfloat16
        )
        comm_fp32 = cmn.create_communicator("tpu", devices=devices8)
        params = {"w": jnp.zeros((4,))}
        x = jnp.stack([jnp.full((4,), float(r)) for r in range(8)])
        outs = []
        for comm in (comm_bf16, comm_fp32):
            opt = cmn.create_multi_node_optimizer(optax.sgd(1.0), comm)
            step = build_train_step(comm, _quadratic_loss, opt, donate=False)
            p, o = step.place(params, opt.init(params))
            p, _, _ = step(p, o, jax.device_put(x, step.batch_sharding))
            outs.append(np.asarray(p["w"]))
        np.testing.assert_allclose(outs[0], outs[1], rtol=2e-2)


class TestDelegation:
    def test_wrapper_exposes_inner(self, comm):
        inner = optax.adam(1e-3)
        opt = cmn.create_multi_node_optimizer(inner, comm)
        assert opt.actual_optimizer is inner
        assert opt.communicator is comm


class TestZeroRedundancy:
    """ZeRO-1 optimizer-state sharding (zero_redundancy=True)."""

    def _run(self, comm, opt, params, n_steps=3):
        step = build_train_step(comm, _quadratic_loss, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        x = jnp.stack([jnp.full(params["w"].shape, float(r)) for r in range(8)])
        bx = jax.device_put(x, step.batch_sharding)
        for _ in range(n_steps):
            p, o, _ = step(p, o, bx)
        return p, o

    def test_matches_plain_adam(self, comm):
        params = {"w": jnp.ones((8,)) * 0.3}
        plain = cmn.create_multi_node_optimizer(optax.adam(0.1), comm)
        zero = cmn.create_multi_node_optimizer(
            optax.adam(0.1), comm, zero_redundancy=True
        )
        p_plain, _ = self._run(comm, plain, params)
        p_zero, _ = self._run(comm, zero, params)
        np.testing.assert_allclose(
            np.asarray(p_plain["w"]), np.asarray(p_zero["w"]), rtol=1e-5
        )

    def test_matches_with_padding(self, comm):
        # 5 elements over 8 shards: blocks are zero-padded
        params = {"w": jnp.asarray([0.1, -0.2, 0.3, 0.5, -0.4])}
        plain = cmn.create_multi_node_optimizer(optax.adam(0.05), comm)
        zero = cmn.create_multi_node_optimizer(
            optax.adam(0.05), comm, zero_redundancy=True
        )
        p_plain, _ = self._run(comm, plain, params)
        p_zero, _ = self._run(comm, zero, params)
        np.testing.assert_allclose(
            np.asarray(p_plain["w"]), np.asarray(p_zero["w"]), rtol=1e-5
        )

    def test_state_is_sharded_one_block_per_chip(self, comm):
        params = {"w": jnp.ones((16,))}
        zero = cmn.create_multi_node_optimizer(
            optax.adam(0.1), comm, zero_redundancy=True
        )
        _, opt_state = self._run(comm, zero, params, n_steps=1)
        # Adam mu leaf: global shape (8, 2), each chip holds one (1, 2) block
        mu = opt_state.inner_state[0].mu["w"]
        assert mu.shape == (8, 2)
        shard_shapes = {s.data.shape for s in mu.addressable_shards}
        assert shard_shapes == {(1, 2)}

    def test_per_chip_state_memory_is_one_nth(self, comm):
        """The ZeRO-1 memory claim, measured: per-device optimizer-state
        bytes for a real TransformerLM under adam must drop to ~1/8 on
        the 8-device mesh (exact shard accounting via
        addressable_shards — the same layout a real TPU mesh gets).
        The numbers quoted in docs/performance.md's ZeRO table come
        from this accounting."""
        import jax.tree_util as jtu

        from chainermn_tpu.models.transformer import TransformerLM

        model = TransformerLM(
            vocab_size=8192, d_model=512, n_heads=8, n_layers=4,
            max_len=128, dtype=jnp.float32,
        )
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 128), jnp.int32)
        )
        n_params = sum(
            x.size for x in jtu.tree_leaves(params)
        )

        def per_device_state_bytes(opt):
            step = build_train_step(
                comm, lambda p, b: 0.0 * jnp.sum(b),
                opt, donate=False,
            )
            p, o = step.place(params, opt.init(params))
            dev = comm.devices[0]
            total = 0
            for leaf in jtu.tree_leaves(o):
                if not hasattr(leaf, "addressable_shards"):
                    continue
                for s in leaf.addressable_shards:
                    if s.device == dev:
                        total += s.data.nbytes
            return total

        plain = cmn.create_multi_node_optimizer(optax.adam(0.1), comm)
        zero = cmn.create_multi_node_optimizer(
            optax.adam(0.1), comm, zero_redundancy=True
        )
        b_plain = per_device_state_bytes(plain)
        b_zero = per_device_state_bytes(zero)
        # plain adam replicates mu+nu: ~2 x params x 4B per device
        assert b_plain >= 2 * n_params * 4
        # ZeRO-1 shards them: ~1/8 per device (+ block padding)
        ratio = b_zero / b_plain
        assert ratio < 1 / 6, (
            f"per-device state {b_zero / 1e6:.1f} MB vs plain "
            f"{b_plain / 1e6:.1f} MB (ratio {ratio:.3f})"
        )
        print(
            f"\nZERO1_MEMORY params={n_params} "
            f"plain_MB={b_plain / 1e6:.1f} zero_MB={b_zero / 1e6:.1f} "
            f"ratio={ratio:.4f}"
        )

    def test_zero_with_double_buffering_rejected(self, comm):
        with pytest.raises(ValueError):
            cmn.create_multi_node_optimizer(
                optax.adam(0.1), comm, double_buffering=True,
                zero_redundancy=True,
            )

    def test_eager_unbound_path_matches(self, comm):
        # Outside shard_map the blocks update full-width — numerics equal
        # the inner optimizer applied directly.
        params = {"w": jnp.ones((8,))}
        grads = {"w": jnp.arange(8.0) / 10.0}
        inner = optax.adam(0.1)
        zero = cmn.create_multi_node_optimizer(
            inner, comm, zero_redundancy=True
        )
        zstate = zero.init(params)
        zupd, _ = zero.update(grads, zstate, params)
        istate = inner.init(params)
        iupd, _ = inner.update(grads, istate, params)
        np.testing.assert_allclose(
            np.asarray(zupd["w"]), np.asarray(iupd["w"]), rtol=1e-6
        )

"""Composed-parallelism tests: MoE transformer on a (data, seq, model) mesh.

The oracle is **mesh-factorization invariance**: the SAME
``MoeTransformerLM`` runs on a ``(1,1,1)`` mesh (every axis width 1 — all
collectives degenerate) and on a ``(2,2,2)`` mesh (DP x SP ring attention
x TP Megatron x EP all_to_all all live), with identical global parameter
values and ample expert capacity (no token drops).  Losses and updated
parameters must agree — which exercises every collective the composition
inserts: ring ppermute, sp_lm_loss boundary exchange, column/row TP
psums, EP all_to_all dispatch/return, and the vma-generated gradient
reductions over all three axes.

Reference anchor: the reference composed at most DP x hand-built MP via
``CommunicatorBase.split`` (SURVEY.md section 2 strategy table); SP and EP
are the new capabilities its ``alltoall``/p2p primitives point at
(SURVEY.md section 5.7).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

import chainermn_tpu as cmn
from chainermn_tpu.models.moe_transformer import (
    MoeMlp,
    MoeTransformerLM,
    moe_lm_loss,
    moe_param_specs,
)
from chainermn_tpu.optimizers import build_train_step
from chainermn_tpu.parallel import sharded_init

VOCAB, D, HEADS, LAYERS, EXPERTS, FF = 61, 32, 4, 2, 4, 64
B, S = 4, 16
CAP = B * S * 2  # >= total routed claims: nothing is ever dropped


def _model(comm=None, capacity=CAP):
    kw = {}
    if comm is not None:
        # aux_stat_axes over every token-splitting axis: the
        # load-balancing loss becomes the exact global-batch value, so
        # the factorization oracle can run with the aux term ON.
        kw = dict(seq_axis="mn_seq", tp_axis="mn_model",
                  expert_axis="mn_model",
                  aux_stat_axes=("mn_data", "mn_seq", "mn_model"))
    return MoeTransformerLM(
        vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=LAYERS,
        n_experts=EXPERTS, d_ff=FF, moe_every=2, k=2, capacity=capacity,
        max_len=S, dtype=jnp.float32, **kw,
    )


def _tokens(seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randint(0, VOCAB, (B, S)), jnp.int32
    )


def _init_on(comm):
    model = _model(comm)
    toks = _tokens()
    params, specs = sharded_init(
        lambda t: model.init(jax.random.PRNGKey(0), t),
        comm.mesh, (P("mn_data", "mn_seq"),),
        moe_param_specs, toks,
    )
    return model, params, specs


def _host_tree(params):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), params)


def _run_steps(comm, params_host, n_steps=2, lr=5e-2, aux_coef=1e-2):
    model = _model(comm)
    specs = moe_param_specs(params_host)
    opt = cmn.create_multi_node_optimizer(optax.sgd(lr), comm)

    def loss_fn(p, b):
        return moe_lm_loss(
            model.apply(p, b), b, seq_axis="mn_seq",
            model_axis="mn_model", aux_coef=aux_coef,
        )

    step = build_train_step(
        comm, loss_fn, opt, data_axes=comm.data_axis_names,
        param_specs=specs, batch_specs=P("mn_data", "mn_seq"),
        donate=False,
    )
    params, opt_state = step.place(params_host, opt.init(params_host))
    batch = step.place_batch(_tokens())
    losses = []
    for _ in range(n_steps):
        params, opt_state, m = step(params, opt_state, batch)
        losses.append(float(m["loss"]))
    return params, losses


class TestMeshCommunicator:
    def test_axes_and_sizes(self, devices8):
        comm = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=2, tp_size=2
        )
        assert comm.axis_names == ("mn_data", "mn_seq", "mn_model")
        assert (comm.dp_size, comm.sp_size, comm.tp_size) == (2, 2, 2)
        assert dict(comm.mesh.shape) == {
            "mn_data": 2, "mn_seq": 2, "mn_model": 2
        }

    def test_sizes_must_divide(self, devices8):
        with pytest.raises(ValueError, match="divide"):
            cmn.create_communicator(
                "mesh", devices=devices8, sp_size=3, tp_size=2
            )

    def test_width_one_axes_are_plain_dp(self, devices8):
        comm = cmn.create_communicator("mesh", devices=devices8)
        assert (comm.dp_size, comm.sp_size, comm.tp_size) == (8, 1, 1)


# jax 0.4.x tier: the composed hybrid step's gradient sync relies on
# current jax's vma machinery; the compat fallback (check_rep=False +
# a static per-leaf rep-sum, chainermn_tpu/_compat.py) is exact for
# Megatron-style DP x TP graphs (test_hybrid pins that) but not for the
# composed MoE/seq-parallel graph, where whether a replicated leaf's
# cotangent needs a cross-axis psum depends on value-varyingness the
# static rule cannot see.  Forward numerics still match exactly (the
# loss-equality first step passes); the post-update trajectories drift.
_old_jax_vma = pytest.mark.xfail(
    __import__("chainermn_tpu._compat", fromlist=["OLD_SHARD_MAP"]).OLD_SHARD_MAP,
    strict=False,
    reason="composed-graph gradient rep-sum needs current-jax vma",
)


class TestFactorizationOracle:
    """(1,1,1) vs (2,2,2): same global params, same numerics."""

    @pytest.fixture(scope="class")
    def runs(self, devices8):
        comm222 = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=2, tp_size=2
        )
        comm111 = cmn.create_communicator(
            "mesh", devices=devices8[:1], sp_size=1, tp_size=1
        )
        _, params, _ = _init_on(comm222)
        host = _host_tree(params)
        p222, l222 = _run_steps(comm222, host)
        p111, l111 = _run_steps(comm111, host)
        return (
            _host_tree(p222), l222, _host_tree(p111), l111
        )

    @_old_jax_vma
    def test_losses_match(self, runs):
        _, l222, _, l111 = runs
        np.testing.assert_allclose(l222, l111, rtol=2e-4, atol=1e-5)

    @_old_jax_vma
    def test_updated_params_match(self, runs):
        p222, _, p111, _ = runs
        flat222 = jax.tree_util.tree_leaves_with_path(p222)
        flat111 = dict(jax.tree_util.tree_leaves_with_path(p111))
        assert flat222
        for path, leaf in flat222:
            want = flat111[path]
            np.testing.assert_allclose(
                leaf, want, rtol=5e-4, atol=2e-5,
                err_msg=jax.tree_util.keystr(path),
            )

    def test_expert_and_tp_leaves_are_sharded(self, devices8):
        comm = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=2, tp_size=2
        )
        _, params, specs = _init_on(comm)
        flat = jax.tree_util.tree_leaves_with_path(params)
        by_name = {jax.tree_util.keystr(p): v for p, v in flat}
        w1 = next(v for k, v in by_name.items()
                  if k.endswith("['expert_w1']"))
        assert w1.shape == (EXPERTS, D, FF)  # global expert dim
        assert {s.data.shape for s in w1.addressable_shards} == {
            (EXPERTS // 2, D, FF)
        }
        up = next(v for k, v in by_name.items()
                  if "TpMlpBlock" in k and "ColumnParallel" in k
                  and k.endswith("['kernel']"))
        assert up.shape == (D, FF)
        assert {s.data.shape for s in up.addressable_shards} == {
            (D, FF // 2)
        }


class TestComposedVocabParallel:
    """The fully-loaded flagship: DP x SP(ring) x TP x EP PLUS the
    vocab-parallel embedding/head — factorization oracle on a
    64-vocab model (divisible by the model-axis width)."""

    def _run(self, comm, params_host, n_steps=2):
        model = MoeTransformerLM(
            vocab_size=64, d_model=D, n_heads=HEADS, n_layers=LAYERS,
            n_experts=EXPERTS, d_ff=FF, moe_every=2, k=2, capacity=CAP,
            max_len=S, dtype=jnp.float32, seq_axis="mn_seq",
            tp_axis="mn_model", expert_axis="mn_model",
            vocab_parallel=True,
            aux_stat_axes=("mn_data", "mn_seq", "mn_model"),
        )
        specs = moe_param_specs(params_host)
        opt = cmn.create_multi_node_optimizer(optax.sgd(5e-2), comm)

        def loss_fn(p, b):
            return moe_lm_loss(
                model.apply(p, b), b, seq_axis="mn_seq",
                model_axis="mn_model", aux_coef=1e-2,
                vocab_parallel=True,
            )

        step = build_train_step(
            comm, loss_fn, opt, data_axes=comm.data_axis_names,
            param_specs=specs, batch_specs=P("mn_data", "mn_seq"),
            donate=False,
        )
        params, opt_state = step.place(params_host, opt.init(params_host))
        toks = jnp.asarray(
            np.random.RandomState(2).randint(0, 64, (B, S)), jnp.int32
        )
        batch = step.place_batch(toks)
        losses = []
        for _ in range(n_steps):
            params, opt_state, m = step(params, opt_state, batch)
            losses.append(float(m["loss"]))
        return _host_tree(params), losses

    @_old_jax_vma
    def test_factorizations_agree(self, devices8):
        comm222 = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=2, tp_size=2
        )
        comm111 = cmn.create_communicator(
            "mesh", devices=devices8[:1], sp_size=1, tp_size=1
        )
        model = MoeTransformerLM(
            vocab_size=64, d_model=D, n_heads=HEADS, n_layers=LAYERS,
            n_experts=EXPERTS, d_ff=FF, moe_every=2, k=2, capacity=CAP,
            max_len=S, dtype=jnp.float32, seq_axis="mn_seq",
            tp_axis="mn_model", expert_axis="mn_model",
            vocab_parallel=True,
            aux_stat_axes=("mn_data", "mn_seq", "mn_model"),
        )
        toks = jnp.asarray(
            np.random.RandomState(2).randint(0, 64, (B, S)), jnp.int32
        )
        params, _ = sharded_init(
            lambda t: model.init(jax.random.PRNGKey(0), t),
            comm222.mesh, (P("mn_data", "mn_seq"),), moe_param_specs,
            toks,
        )
        emb = params["params"]["VocabParallelEmbed_0"]["embedding"]
        assert emb.shape == (64, D)  # global vocab dim
        assert {sh.data.shape for sh in emb.addressable_shards} == {
            (32, D)
        }
        host = _host_tree(params)
        p222, l222 = self._run(comm222, host)
        p111, l111 = self._run(comm111, host)
        assert all(np.isfinite(l222))
        np.testing.assert_allclose(l222, l111, rtol=2e-4, atol=1e-5)
        flat111 = dict(jax.tree_util.tree_leaves_with_path(p111))
        for path, leaf in jax.tree_util.tree_leaves_with_path(p222):
            np.testing.assert_allclose(
                leaf, flat111[path], rtol=5e-4, atol=2e-5,
                err_msg=jax.tree_util.keystr(path),
            )


class TestComposedTraining:
    def test_loss_decreases_with_aux(self, devices8):
        comm = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=2, tp_size=2
        )
        _, params, _ = _init_on(comm)
        _, losses = _run_steps(
            comm, _host_tree(params), n_steps=6, lr=0.1, aux_coef=1e-2
        )
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestShardedCheckpoint:
    """Checkpoint/resume round-trip with mesh-sharded parameters: TP
    kernels and expert blocks live sharded over mn_model; a snapshot
    taken mid-run must restore into an identical continued training
    trajectory (SURVEY.md section 2 #29, arrays now global/sharded)."""

    def test_resume_matches_uninterrupted(self, devices8, tmp_path):
        comm = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=2, tp_size=2
        )
        _, params0, _ = _init_on(comm)
        host = _host_tree(params0)

        # uninterrupted: 2 steps
        p_full, _ = _run_steps(comm, host, n_steps=2)

        # interrupted: 1 step, checkpoint, restore, 1 more step
        model = _model(comm)
        specs = moe_param_specs(host)
        opt = cmn.create_multi_node_optimizer(optax.sgd(5e-2), comm)

        def loss_fn(p, b):
            return moe_lm_loss(
                model.apply(p, b), b, seq_axis="mn_seq",
                model_axis="mn_model", aux_coef=1e-2,
            )

        step = build_train_step(
            comm, loss_fn, opt, data_axes=comm.data_axis_names,
            param_specs=specs, batch_specs=P("mn_data", "mn_seq"),
            donate=False,
        )
        params, opt_state = step.place(host, opt.init(host))
        batch = step.place_batch(_tokens())
        params, opt_state, _ = step(params, opt_state, batch)

        ckpt = cmn.create_multi_node_checkpointer(
            "moe", comm, path=str(tmp_path)
        )
        ckpt.save(1, {"params": params, "opt_state": opt_state})

        restored_step, state = ckpt.resume(
            like={"params": params, "opt_state": opt_state}
        )
        assert restored_step == 1
        # re-place per the sharding specs (restore may yield host arrays)
        rparams, ropt = step.place(state["params"], state["opt_state"])
        rparams, ropt, _ = step(rparams, ropt, batch)

        flat_full = dict(jax.tree_util.tree_leaves_with_path(
            _host_tree(p_full)
        ))
        for path, leaf in jax.tree_util.tree_leaves_with_path(
            _host_tree(rparams)
        ):
            np.testing.assert_allclose(
                leaf, flat_full[path], rtol=1e-6, atol=1e-7,
                err_msg=jax.tree_util.keystr(path),
            )


class TestMoeMlpDenseVsParallel:
    """The expert_axis=None tier is the numerics oracle for the EP path."""

    def test_dense_matches_expert_parallel(self, devices8):
        mesh = cmn.create_communicator(
            "mesh", devices=devices8[:2], sp_size=1, tp_size=2
        ).mesh
        cap = 64
        par = MoeMlp(n_experts=4, d_ff=32, k=2, capacity=cap,
                     expert_axis="mn_model", dtype=jnp.float32)
        dense = MoeMlp(n_experts=4, d_ff=32, k=2, capacity=cap,
                       expert_axis=None, dtype=jnp.float32)
        x = jnp.asarray(
            np.random.RandomState(3).randn(2, 8, 16), jnp.float32
        )

        def init_fn(xx):
            return par.init(jax.random.PRNGKey(1), xx)

        params, _ = sharded_init(
            init_fn, mesh, (P(),),
            lambda p: moe_param_specs(p, model_axis="mn_model"), x,
        )
        y_par = jax.jit(
            jax.shard_map(
                lambda p, xx: par.apply(p, xx)[0],
                mesh=mesh,
                in_specs=(moe_param_specs(params), P()),
                out_specs=P(), check_vma=False,
            )
        )(params, x)
        y_dense, aux_dense = dense.apply(_host_tree(params), x)
        np.testing.assert_allclose(
            np.asarray(y_par), np.asarray(y_dense), rtol=1e-5, atol=1e-6
        )
        assert np.isfinite(float(aux_dense))

    def test_capacity_drop_zeroes_tokens(self):
        """With capacity 1 and concentrated routing, overflow tokens
        contribute zeros (standard MoE drop semantics)."""
        m = MoeMlp(n_experts=2, d_ff=8, k=1, capacity=1,
                   expert_axis=None, dtype=jnp.float32)
        x = jnp.ones((1, 4, 6), jnp.float32)  # identical tokens
        params = m.init(jax.random.PRNGKey(0), x)
        y, _ = m.apply(params, x)
        # identical tokens route identically: 1 kept per expert per
        # claim-route, the rest dropped -> some rows exactly zero
        rows = np.asarray(y)[0]
        assert (np.abs(rows).sum(axis=-1) == 0).any()


class TestVocabParallel:
    """Megatron vocab-parallel embedding + cross entropy: the (.., V)
    logits row never materializes; numerics must match the dense path."""

    def test_cross_entropy_matches_optax(self, devices8):
        import optax
        from jax.sharding import Mesh, NamedSharding

        from chainermn_tpu.parallel import vocab_parallel_cross_entropy

        mesh2 = Mesh(np.array(devices8[:2]), ("tp",))
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(4, 10, 32), jnp.float32)
        targets = jnp.asarray(rng.randint(0, 32, (4, 10)), jnp.int32)
        want = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        )
        f = jax.jit(
            jax.shard_map(
                lambda lg, t: vocab_parallel_cross_entropy(lg, t, "tp"),
                mesh=mesh2,
                in_specs=(P(None, None, "tp"), P()),
                out_specs=P(), check_vma=False,
            )
        )
        got = f(
            jax.device_put(
                logits, NamedSharding(mesh2, P(None, None, "tp"))
            ),
            targets,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6
        )

    def test_embed_matches_dense_lookup(self, devices8):
        from jax.sharding import Mesh
        from chainermn_tpu.parallel import VocabParallelEmbed
        from chainermn_tpu.parallel.tensor_parallel import _tp_leaf_spec

        mesh2 = cmn.create_communicator(
            "mesh", devices=devices8[:2], sp_size=1, tp_size=2
        ).mesh
        vp = VocabParallelEmbed(32, 8, axis_name="mn_model")
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 32, (3, 5)), jnp.int32
        )
        params, _ = sharded_init(
            lambda t: vp.init(jax.random.PRNGKey(0), t),
            mesh2, (P(),),
            lambda p: jax.tree_util.tree_map(
                lambda _: P("mn_model", None), p
            ),
            toks,
        )
        table = np.asarray(params["params"]["embedding"])  # global (32, 8)
        assert table.shape == (32, 8)
        out = jax.jit(
            jax.shard_map(
                lambda p, t: vp.apply(p, t),
                mesh=mesh2,
                in_specs=(
                    jax.tree_util.tree_map(
                        lambda _: P("mn_model", None), params
                    ),
                    P(),
                ),
                out_specs=P(), check_vma=False,
            )
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out), table[np.asarray(toks)], rtol=1e-6
        )

    def _run_vp(self, comm, params_host, n_steps=2):
        from chainermn_tpu.models.transformer import (
            TransformerLM,
            vp_lm_loss,
        )
        from chainermn_tpu.parallel import megatron_param_specs

        model = TransformerLM(
            vocab_size=64, d_model=D, n_heads=HEADS, n_layers=2,
            max_len=S, dtype=jnp.float32, tp_axis="mn_model",
            vocab_parallel=True,
        )
        specs = megatron_param_specs(params_host, model_axis="mn_model")
        opt = cmn.create_multi_node_optimizer(optax.sgd(5e-2), comm)

        def loss_fn(p, b):
            return vp_lm_loss(model.apply(p, b), b, "mn_model")

        step = build_train_step(
            comm, loss_fn, opt, data_axes=comm.data_axis_names,
            param_specs=specs, batch_specs=P("mn_data"), donate=False,
        )
        params, opt_state = step.place(params_host, opt.init(params_host))
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, 64, (8, S)), jnp.int32
        )
        batch = step.place_batch(toks)
        losses = []
        for _ in range(n_steps):
            params, opt_state, m = step(params, opt_state, batch)
            losses.append(float(m["loss"]))
        return _host_tree(params), losses

    def test_vp_lm_factorization_oracle(self, devices8):
        from chainermn_tpu.models.transformer import TransformerLM
        from chainermn_tpu.parallel import megatron_param_specs

        comm_tp = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=1, tp_size=2
        )
        comm_dp = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=1, tp_size=1
        )
        model = TransformerLM(
            vocab_size=64, d_model=D, n_heads=HEADS, n_layers=2,
            max_len=S, dtype=jnp.float32, tp_axis="mn_model",
            vocab_parallel=True,
        )
        params, _ = sharded_init(
            lambda t: model.init(jax.random.PRNGKey(0), t),
            comm_tp.mesh, (P("mn_data"),),
            lambda p: megatron_param_specs(p, model_axis="mn_model"),
            jnp.zeros((4, S), jnp.int32),
        )
        # embedding is genuinely vocab-sharded on the TP mesh
        emb = params["params"]["VocabParallelEmbed_0"]["embedding"]
        assert emb.shape == (64, D)
        assert {sh.data.shape for sh in emb.addressable_shards} == {
            (32, D)
        }
        host = _host_tree(params)
        p_tp, l_tp = self._run_vp(comm_tp, host)
        p_dp, l_dp = self._run_vp(comm_dp, host)
        np.testing.assert_allclose(l_tp, l_dp, rtol=2e-4, atol=1e-5)
        flat_dp = dict(jax.tree_util.tree_leaves_with_path(p_dp))
        for path, leaf in jax.tree_util.tree_leaves_with_path(p_tp):
            np.testing.assert_allclose(
                leaf, flat_dp[path], rtol=5e-4, atol=2e-5,
                err_msg=jax.tree_util.keystr(path),
            )

    def test_vocab_parallel_without_tp_axis_rejected(self):
        from chainermn_tpu.models.transformer import TransformerLM

        model = TransformerLM(
            vocab_size=64, d_model=D, n_heads=HEADS, n_layers=1,
            max_len=S, dtype=jnp.float32, vocab_parallel=True,
        )
        with pytest.raises(ValueError, match="vocab_parallel"):
            model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, S), jnp.int32)
            )


class TestTpOnlyTransformer:
    """TransformerLM(tp_axis=...) factorization oracle: (8,1,1) vs
    (4,1,2) — Megatron attention + MLP sharding changes nothing."""

    def _run(self, comm, params_host, n_steps=2):
        from chainermn_tpu.models.transformer import TransformerLM
        from chainermn_tpu.parallel import megatron_param_specs

        model = TransformerLM(
            vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=2,
            max_len=S, dtype=jnp.float32, tp_axis="mn_model",
        )
        specs = megatron_param_specs(params_host, model_axis="mn_model")
        opt = cmn.create_multi_node_optimizer(optax.sgd(5e-2), comm)

        def loss_fn(p, b):
            from chainermn_tpu.models.transformer import lm_loss

            return lm_loss(model.apply(p, b), b)

        step = build_train_step(
            comm, loss_fn, opt, data_axes=comm.data_axis_names,
            param_specs=specs, batch_specs=P("mn_data"), donate=False,
        )
        params, opt_state = step.place(params_host, opt.init(params_host))
        toks = jnp.asarray(
            np.random.RandomState(1).randint(0, VOCAB, (8, S)), jnp.int32
        )
        batch = step.place_batch(toks)
        losses = []
        for _ in range(n_steps):
            params, opt_state, m = step(params, opt_state, batch)
            losses.append(float(m["loss"]))
        return _host_tree(params), losses

    def test_tp_matches_width_one(self, devices8):
        from chainermn_tpu.models.transformer import TransformerLM
        from chainermn_tpu.parallel import megatron_param_specs

        comm_tp = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=1, tp_size=2
        )
        comm_dp = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=1, tp_size=1
        )
        model = TransformerLM(
            vocab_size=VOCAB, d_model=D, n_heads=HEADS, n_layers=2,
            max_len=S, dtype=jnp.float32, tp_axis="mn_model",
        )
        params, _ = sharded_init(
            lambda t: model.init(jax.random.PRNGKey(0), t),
            comm_tp.mesh, (P("mn_data"),),
            lambda p: megatron_param_specs(p, model_axis="mn_model"),
            _tokens(1),
        )
        host = _host_tree(params)
        p_tp, l_tp = self._run(comm_tp, host)
        p_dp, l_dp = self._run(comm_dp, host)
        np.testing.assert_allclose(l_tp, l_dp, rtol=2e-4, atol=1e-5)
        flat_dp = dict(jax.tree_util.tree_leaves_with_path(p_dp))
        for path, leaf in jax.tree_util.tree_leaves_with_path(p_tp):
            np.testing.assert_allclose(
                leaf, flat_dp[path], rtol=5e-4, atol=2e-5,
                err_msg=jax.tree_util.keystr(path),
            )

"""True multi-process distributed tests.

Parity: the reference's execution model ``mpiexec -n 2 pytest tests/``
(SURVEY.md section 4) — no mocks, a real distributed runtime.  Here each
test spawns N fresh Python processes that rendezvous through
``jax.distributed.initialize`` on a local coordinator, with virtual CPU
devices standing in for per-host chips; scenarios live in
``tests/mp_worker.py``.

These are the only tests that execute the multi-host-only code paths:
``MultiprocessObjStore`` (KV-store send/recv, host-collective bcast/
gather), ``broadcast_one_to_all`` in ``bcast_data``, the
``make_array_from_process_local_data`` branch of ``_place_batch``,
checkpoint save/agree/resume across processes, ``barrier``, and the
global except hook's distributed shutdown.

Run just these:   pytest -m multiprocess tests/
Skip them:        pytest -m "not multiprocess" tests/
"""

import json
import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.multiprocess

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "mp_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_world(scenario, n_procs=2, local_devices=1, tmpdir="/tmp",
              timeout=240, extra_env=None):
    """Spawn ``n_procs`` workers; return list of (returncode, stdout)."""
    from conftest import subprocess_env

    port = _free_port()
    # the ambient env may point JAX at the (single-claim) TPU tunnel;
    # workers must build their own CPU world (subprocess_env pops
    # JAX_PLATFORMS and forces the virtual device count)
    env = subprocess_env(local_devices)
    env.update(extra_env or {})
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, scenario, str(port), str(i),
             str(n_procs), str(tmpdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(n_procs)
    ]
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            results.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return results


def _assert_ok(results, scenario):
    payloads = []
    for i, (rc, out) in enumerate(results):
        assert rc == 0, (
            f"{scenario}: process {i} exited {rc}\n--- output ---\n{out[-4000:]}"
        )
        line = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert line, f"{scenario}: process {i} printed no RESULT\n{out[-2000:]}"
        payloads.append(json.loads(line[-1][len("RESULT "):]))
    return payloads


class TestObjTransport:
    def test_two_processes(self, tmp_path):
        res = run_world("obj_transport", n_procs=2, tmpdir=tmp_path)
        payloads = _assert_ok(res, "obj_transport")
        assert all(p["size"] == 2 for p in payloads)

    def test_four_processes(self, tmp_path):
        res = run_world("obj_transport", n_procs=4, tmpdir=tmp_path)
        payloads = _assert_ok(res, "obj_transport")
        assert all(p["size"] == 4 for p in payloads)


class TestBcastData:
    def test_bit_identity_across_processes(self, tmp_path):
        res = run_world("bcast_data", n_procs=2, local_devices=2,
                        tmpdir=tmp_path)
        _assert_ok(res, "bcast_data")


class TestTrainStep:
    def test_per_process_batch_placement_and_sync(self, tmp_path):
        # 2 processes x 2 local devices = 4-chip world
        res = run_world("train_step", n_procs=2, local_devices=2,
                        tmpdir=tmp_path)
        payloads = _assert_ok(res, "train_step")
        # both controllers hold the same replicated params
        assert payloads[0]["final_w"] == pytest.approx(
            payloads[1]["final_w"]
        )


class TestComposedMesh:
    def test_dp_sp_tp_ep_across_processes(self, tmp_path):
        # 2 processes x 4 local devices = (2, 2, 2) mesh spanning hosts:
        # the data axis crosses the process boundary, so the composed
        # MoE step's gradient psum and per-process batch placement ride
        # the multi-controller path for real.
        res = run_world("composed_mesh", n_procs=2, local_devices=4,
                        tmpdir=tmp_path, timeout=420)
        payloads = _assert_ok(res, "composed_mesh")
        assert payloads[0]["losses"] == pytest.approx(
            payloads[1]["losses"]
        )


class TestCheckpoint:
    def test_save_agree_resume(self, tmp_path):
        res = run_world("checkpoint", n_procs=2, local_devices=2,
                        tmpdir=tmp_path)
        payloads = _assert_ok(res, "checkpoint")
        assert all(p["resumed_step"] == 7 for p in payloads)


class TestIterators:
    def test_multi_node_and_synchronized(self, tmp_path):
        # 2 processes x 2 local devices: rank_master=3 lives on process 1,
        # so the per-batch bcast_obj must relay the *master's* stream
        # (and out-of-range roots must raise on every process).
        res = run_world("iterators", n_procs=2, local_devices=2,
                        tmpdir=tmp_path)
        payloads = _assert_ok(res, "iterators")
        assert payloads[0]["first_batch"] == payloads[1]["first_batch"]


class TestAllreducePersistent:
    def test_cross_process_mean(self, tmp_path):
        res = run_world("allreduce_persistent", n_procs=2, tmpdir=tmp_path)
        _assert_ok(res, "allreduce_persistent")


class TestBarrier:
    def test_barrier_rendezvous(self, tmp_path):
        res = run_world("barrier", n_procs=2, tmpdir=tmp_path)
        payloads = _assert_ok(res, "barrier")
        assert payloads[0]["waited"] >= 1.0


class TestKillMidCheckpoint:
    def test_agreement_survives_rank_death_after_save(self, tmp_path):
        """The agreement protocol's reason-for-existence (VERDICT r4
        #6): rank 1 writes step 3's snapshot to its local disk and dies
        before the agreement round; on restart the world must agree on
        step 2 (the newest step on ALL ranks), ignore rank 1's newer
        snapshot, restore step 2's exact params everywhere, and keep
        training on the closed-form trajectory."""
        # run A: rank 1 exits 42 by design after writing step 3
        res = run_world("kill_mid_checkpoint_phase1", n_procs=2,
                        tmpdir=tmp_path)
        rc0, out0 = res[0]
        rc1, out1 = res[1]
        assert rc0 == 0, f"rank 0 should survive run A\n{out0[-3000:]}"
        assert rc1 == 42, (
            f"rank 1 should die (42) after writing step 3\n{out1[-3000:]}"
        )
        assert "RANK1_WROTE_STEP3_AND_DIED" in out1
        # run B: fresh world over the same scratch — agree on N-1=2,
        # resume, continue
        res = run_world("kill_mid_checkpoint_phase2", n_procs=2,
                        tmpdir=tmp_path)
        payloads = _assert_ok(res, "kill_mid_checkpoint_phase2")
        assert all(p["resumed_step"] == 2 for p in payloads)
        assert payloads[0]["w4"] == pytest.approx(payloads[1]["w4"])


class TestAsyncCheckpoint:
    def test_async_save_agree_resume_two_processes(self, tmp_path):
        # use_async=True was previously only exercised single-process;
        # here the AsyncCheckpointer's background commit, the
        # save-after-save serialization, wait_until_finished, and the
        # agreement protocol all run across a real 2-process world.
        res = run_world("async_checkpoint", n_procs=2, local_devices=2,
                        tmpdir=tmp_path)
        payloads = _assert_ok(res, "async_checkpoint")
        assert all(p["resumed_step"] == 5 for p in payloads)


class TestResilience:
    def test_retry_skip_and_auto_resume_two_processes(self, tmp_path):
        """Tentpole acceptance in a real 2-process world: an injected
        transient obj-store timeout is retried and the run completes; a
        NaN gradient on one process is skipped in agreement on all
        ranks with no deadlock; an injected mid-run failure triggers
        auto-resume from newest_common_step() with max_restarts
        respected (faults reach the workers via CHAINERMN_TPU_FAULTS)."""
        import json as _json

        faults = _json.dumps([
            {"site": "obj_store.exchange", "kind": "timeout", "at": [1]},
            {"site": "trainer.update", "kind": "timeout", "at": [4]},
        ])
        res = run_world(
            "resilience", n_procs=2, local_devices=2, tmpdir=tmp_path,
            timeout=420,
            extra_env={"CHAINERMN_TPU_FAULTS": faults},
        )
        payloads = _assert_ok(res, "resilience")
        assert all(p["restarts"] == 1 for p in payloads)
        assert payloads[0]["final_w"] == pytest.approx(
            payloads[1]["final_w"]
        )


class TestWireInt8:
    def test_bucketed_int8_wire_under_fault_injector(self, tmp_path):
        """ISSUE 4 satellite: the bucketed+int8 gradient wire end to end
        in a real 2-process world.  The FIRST obj-store exchange (the
        bucket-plan-hash agreement) ships a truncated payload on every
        process -> PayloadCorruptionError everywhere in lockstep ->
        plan_agreement retries -> the compiled int8+error-feedback run
        completes with bit-identical params on both processes."""
        import json as _json

        faults = _json.dumps([
            {"site": "obj_store.exchange", "kind": "truncate", "at": [1],
             "truncate_to": 4},
        ])
        res = run_world(
            "wire_int8", n_procs=2, local_devices=2, tmpdir=tmp_path,
            timeout=420,
            extra_env={"CHAINERMN_TPU_FAULTS": faults},
        )
        payloads = _assert_ok(res, "wire_int8")
        assert all(p["faults"] >= 1 for p in payloads)
        assert all(p["final_loss"] < p["first_loss"] for p in payloads)

    def test_overlap_step_under_fault_injector(self, tmp_path):
        """ISSUE 8 satellite: a 2-proc compiled OVERLAPPED step under
        the fault injector — retried transients on the plan-agreement
        and trace-guard exchanges must not reorder or drop any bucket:
        the trace hash is stable across the faulted run (and across
        ranks), every bucket psum still issues at its dependency
        frontier, and loss/params are bit-identical to the no-fault
        synchronous run (asserted inside the scenario)."""
        import json as _json

        faults = _json.dumps([
            {"site": "obj_store.exchange", "kind": "truncate",
             "at": [1, 3], "truncate_to": 4},
        ])
        res = run_world(
            "overlap_fault", n_procs=2, local_devices=2, tmpdir=tmp_path,
            timeout=420,
            extra_env={"CHAINERMN_TPU_FAULTS": faults},
        )
        payloads = _assert_ok(res, "overlap_fault")
        assert all(p["faults"] >= 2 for p in payloads)
        assert all(p["buckets"] >= 3 for p in payloads)

    def test_multihop_schedule_under_fault_injector(self, tmp_path):
        """ISSUE 11 satellite: the hier_rs_ag multi-hop wire across a
        REAL 2-process hierarchical world (process grouping = slice
        grouping, so the mesh factorizes (2, 2)) with truncate faults
        injected during schedule/plan agreement — the lockstep retry
        completes, every rank lands on the same WirePlan hash (bucket
        layout AND schedule), the trace carries the rs→ar→ag triple
        per bucket and hashes identically across ranks and across the
        faulted run, and loss/params are bit-identical to the no-fault
        run (all asserted inside the scenario)."""
        import json as _json

        faults = _json.dumps([
            {"site": "obj_store.exchange", "kind": "truncate",
             "at": [1, 3], "truncate_to": 4},
        ])
        res = run_world(
            "multihop_fault", n_procs=2, local_devices=2,
            tmpdir=tmp_path, timeout=420,
            extra_env={"CHAINERMN_TPU_FAULTS": faults},
        )
        payloads = _assert_ok(res, "multihop_fault")
        assert all(p["faults"] >= 2 for p in payloads)
        assert all(p["buckets"] >= 3 for p in payloads)
        assert all(
            p["mesh"] == {"mn_inter": 2, "mn_intra": 2}
            for p in payloads
        )
        assert payloads[0]["final_loss"] == payloads[1]["final_loss"]

    def test_tuned_wire_under_fault_and_profile_mismatch(self, tmp_path):
        """ISSUE 12 satellite: both ranks load ONE BandwidthProfile
        from the shared scratch and tune through it — truncate faults
        on the plan-agreement exchanges are retried in lockstep and the
        agreed WirePlan hash (which now folds in the profile content
        hash) matches across ranks, with the profile-staged rs→ar→ag
        triple in the trace; then a deliberately perturbed profile on
        rank 1 makes a fresh optimizer's init raise
        WirePlanMismatchError on BOTH ranks before any collective (all
        asserted inside the scenario)."""
        import json as _json

        faults = _json.dumps([
            {"site": "obj_store.exchange", "kind": "truncate",
             "at": [1, 3], "truncate_to": 4},
        ])
        res = run_world(
            "tuned_wire_fault", n_procs=2, local_devices=2,
            tmpdir=tmp_path, timeout=420,
            extra_env={"CHAINERMN_TPU_FAULTS": faults},
        )
        payloads = _assert_ok(res, "tuned_wire_fault")
        assert all(p["faults"] >= 2 for p in payloads)
        assert all(p["buckets"] >= 3 for p in payloads)
        assert all(p["mismatch_raised"] for p in payloads)
        # one profile, one plan: every rank agreed on both hashes
        assert payloads[0]["profile_hash"] == payloads[1]["profile_hash"]
        assert payloads[0]["plan_hash"] == payloads[1]["plan_hash"]
        assert payloads[0]["final_loss"] == payloads[1]["final_loss"]


class TestTelemetry:
    def test_straggler_flagged_and_timeline_exported_both_ranks(
        self, tmp_path
    ):
        """ISSUE 10 satellite: a 2-proc run with an injected slow rank
        (delay fault at trainer.update TARGETED at process 1) must
        produce a cross-rank MetricsReport that flags the straggler on
        both ranks, and a fault-injected obj-store retry whose events
        appear in the exported merged timeline in order (validated
        inside the scenario: fault -> retry -> straggler, time-sorted
        JSONL + Chrome-trace JSON shape, per-bucket collective spans
        in the same stream)."""
        import json as _json

        faults = _json.dumps([
            {"site": "obj_store.exchange", "kind": "timeout", "at": [1]},
            {"site": "trainer.update", "kind": "delay", "delay": 0.25,
             "probability": 1.0, "process": 1},
        ])
        res = run_world(
            "telemetry", n_procs=2, local_devices=2, tmpdir=tmp_path,
            timeout=420,
            extra_env={"CHAINERMN_TPU_FAULTS": faults},
        )
        payloads = _assert_ok(res, "telemetry")
        assert all(p["stragglers"] == [1] for p in payloads)
        assert all(p["faults"] >= 1 for p in payloads)
        assert all(p["n_bucket_psums"] >= 2 for p in payloads)
        # both ranks exported their timeline files into the shared dir
        for pid in (0, 1):
            assert (tmp_path / f"trace_p{pid}.json").exists()
            assert (tmp_path / f"trace_p{pid}.jsonl").exists()


class TestTraceDivergence:
    def test_divergent_steps_fail_fast_on_both_ranks(self, tmp_path):
        """ISSUE 5 acceptance: rank 1 builds a step with one extra psum
        (env-selected); the divergence guard exchanges trace hashes at
        the first dispatch and raises CollectiveTraceMismatchError on
        BOTH ranks before any collective runs — instead of the silent
        deadlock this world produces without the guard (this test's
        timeout is the deadlock detector)."""
        res = run_world(
            "trace_divergence", n_procs=2, local_devices=2,
            tmpdir=tmp_path, timeout=240,
            extra_env={"CHAINERMN_TPU_DIVERGE_RANK": "1"},
        )
        payloads = _assert_ok(res, "trace_divergence")
        assert all(
            p["raised"] == "CollectiveTraceMismatchError" for p in payloads
        )


class TestProtocolDivergence:
    def test_guard_raises_on_both_ranks_before_deadlock(self, tmp_path):
        """ISSUE 20 acceptance: rank 1 issues one extra obj-store
        publish and swaps its two agreement-site orderings; the
        host-protocol guard exchanges sequence hashes (through the
        lockstep retry — phase 1 tears the guard's own payload and it
        recovers) and raises ProtocolDivergenceError on BOTH ranks
        while the world is still alive (this test's timeout is the
        deadlock detector).  The per-rank recorded protocols merge
        into the FleetReport post-mortem, which pinpoints the first
        divergent exchange token."""
        res = run_world(
            "protocol_divergence", n_procs=2, local_devices=1,
            tmpdir=tmp_path, timeout=240,
            extra_env={
                "CHAINERMN_TPU_PROTOCOL_RECORD": "1",
                "CHAINERMN_TPU_DIVERGE_RANK": "1",
            },
        )
        payloads = _assert_ok(res, "protocol_divergence")
        assert all(
            p["raised"] == "ProtocolDivergenceError" for p in payloads
        )
        # the torn-then-retried phase-1 agreement converged
        assert payloads[0]["phase1"] == payloads[1]["phase1"]
        assert all(p["entries"] > 0 for p in payloads)

        from chainermn_tpu.fleet.report import FleetReport

        rep = FleetReport.from_scratch(str(tmp_path))
        div = rep.protocol_divergence("protodiv")
        assert div is not None, "merged report must expose the divergence"
        toks = div["tokens"]
        # rank 1's extra publish is the first divergent token
        assert toks[0] != toks[1]
        assert "protocol divergence" in rep.post_mortem()


class TestMismatchedSharding:
    def test_implicit_collectives_fail_both_ranks_before_dispatch(
        self, tmp_path
    ):
        """ISSUE 6 satellite: rank 1's mismatched input sharding makes
        the partitioner insert all-gathers into ITS program only; the
        cross-process ``implicit_agreement`` check raises
        ``ImplicitCollectiveError`` on BOTH ranks before dispatch, with
        the responsible dot_general cited."""
        res = run_world(
            "mismatched_sharding", n_procs=2, local_devices=2,
            tmpdir=tmp_path, timeout=240,
            extra_env={"CHAINERMN_TPU_MISMATCH_RANK": "1"},
        )
        payloads = _assert_ok(res, "mismatched_sharding")
        assert all(
            p["raised"] == "ImplicitCollectiveError" for p in payloads
        )
        assert all(p["cited_dot"] for p in payloads)


class TestSpotReclaim:
    def test_reclaim_reshard_restart_world_2_to_1(self, tmp_path):
        """ISSUE 7 acceptance: a 2-proc ZeRO run saves steps 1-3 (each
        snapshot carrying its world manifest), worker 1 is reclaimed
        mid-step by a process-targeted ``die`` at the injector's
        ``trainer.update`` site, and the restart at world size 1 routes
        the restore through the checkpoint resharder and continues on
        the single-world oracle trajectory."""
        faults = json.dumps([
            {"site": "trainer.update", "kind": "die", "at": [4],
             "process": 1, "exit_code": 43},
        ])
        res = run_world(
            "spot_reclaim_phase1", n_procs=2, tmpdir=tmp_path,
            timeout=420, extra_env={"CHAINERMN_TPU_FAULTS": faults},
        )
        rc0, out0 = res[0]
        rc1, out1 = res[1]
        assert rc0 == 0 and "RESULT" in out0, (
            f"worker 0 should be reaped cleanly after the save\n"
            f"{out0[-3000:]}"
        )
        assert rc1 == 43, (
            f"worker 1 should be reclaimed (exit 43) at update 4\n"
            f"{out1[-3000:]}"
        )
        # run B: the world re-forms at size 1, reshards, and continues
        res = run_world("spot_reclaim_phase2", n_procs=1,
                        tmpdir=tmp_path, timeout=420)
        payloads = _assert_ok(res, "spot_reclaim_phase2")
        assert payloads[0]["resumed_step"] == 3
        assert payloads[0]["resized"] == [2, 1]
        assert payloads[0]["oracle_match"] is True


class TestServingChurn:
    def test_replica_killed_mid_stream_survivor_completes(self, tmp_path):
        """ISSUE 13 satellite: a 2-replica serving world decodes a
        scripted 8-request stream off one shared journal; the fault
        injector kills replica 1 mid-stream (process-targeted ``die``
        at its 3rd decode step).  The drained requests stay journaled;
        the phase-2 world (size 1, via ``serve_elastic``) re-claims and
        completes every one with outputs bit-identical to the no-fault
        run (asserted in-scenario against a fresh oracle engine)."""
        faults = json.dumps([
            {"site": "serving.decode_step", "kind": "die", "at": [3],
             "process": 1, "exit_code": 43},
        ])
        res = run_world(
            "serving_churn_phase1", n_procs=2, tmpdir=tmp_path,
            timeout=420, extra_env={"CHAINERMN_TPU_FAULTS": faults},
        )
        rc0, out0 = res[0]
        rc1, out1 = res[1]
        assert rc0 == 0 and "RESULT" in out0, (
            f"replica 0 should complete its share\n{out0[-3000:]}"
        )
        assert rc1 == 43, (
            f"replica 1 should be killed (exit 43) mid-stream\n"
            f"{out1[-3000:]}"
        )
        line = [l for l in out0.splitlines() if l.startswith("RESULT ")]
        served0 = json.loads(line[-1][len("RESULT "):])["served"]
        assert served0 == ["c0", "c2", "c4", "c6"], served0
        res = run_world("serving_churn_phase2", n_procs=1,
                        tmpdir=tmp_path, timeout=420)
        payloads = _assert_ok(res, "serving_churn_phase2")
        assert payloads[0]["pending_before"] >= 4  # replica 1's share
        assert payloads[0]["completed"] == 8
        assert payloads[0]["bit_identical"] is True


class TestExceptHook:
    def test_crash_contained_not_hung(self, tmp_path):
        # process 1 raises; its hook shuts the distributed client down;
        # process 0 (blocked in recv_obj with a 15s bound) must ALSO die
        # promptly instead of hanging for the full 10-minute default.
        res = run_world(
            "except_hook", n_procs=2, tmpdir=tmp_path, timeout=120,
            extra_env={"CHAINERMN_TPU_OBJ_TIMEOUT_MS": "15000"},
        )
        rc0, out0 = res[0]
        rc1, out1 = res[1]
        assert rc1 != 0, f"raising process exited 0\n{out1[-2000:]}"
        assert "injected failure" in out1
        assert "aborting the distributed job" in out1
        assert rc0 != 0, (
            f"peer process survived a dead-peer recv\n{out0[-2000:]}"
        )

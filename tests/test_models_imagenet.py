"""ImageNet model-zoo tests.

Parity: the reference's ``examples/imagenet/models/{alex,googlenet,
googlenetbn,nin,resnet50}.py`` archs — forward shapes, BN-state handling,
and the has_aux train-step path that carries batch statistics.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

import chainermn_tpu as cmn
from chainermn_tpu import models
from chainermn_tpu.optimizers import build_train_step

IMG = 96  # small enough to be fast, large enough for every stem/pool stack


def _init_and_forward(model, batch=2, img=IMG):
    x = jnp.zeros((batch, img, img, 3), jnp.float32)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x[:1],
    )
    out = model.apply(variables, x, rngs={"dropout": jax.random.PRNGKey(2)})
    return variables, out


@pytest.mark.parametrize("factory", [
    models.AlexNet, models.NIN, models.VGG16, models.GoogLeNet,
])
def test_stateless_arch_forward_shape(factory):
    model = factory(num_classes=11, train=False)
    variables, out = _init_and_forward(model)
    assert out.shape == (2, 11)
    assert out.dtype == jnp.float32
    assert "batch_stats" not in variables


@pytest.mark.parametrize("factory", [
    models.GoogLeNetBN, models.ResNet18,
])
def test_bn_arch_forward_shape(factory):
    model = factory(num_classes=7, train=True)
    x = jnp.zeros((2, IMG, IMG, 3), jnp.float32)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x[:1],
    )
    assert "batch_stats" in variables
    out, mut = model.apply(
        variables, x, mutable=["batch_stats"],
        rngs={"dropout": jax.random.PRNGKey(2)},
    )
    assert out.shape == (2, 7)
    assert jax.tree_util.tree_structure(
        mut["batch_stats"]
    ) == jax.tree_util.tree_structure(variables["batch_stats"])


def test_bf16_bn_numerics_close_to_fp32_and_stats_stay_fp32():
    """The default norm normalizes in the model's compute dtype (the
    round-3 MFU lever: bf16 arithmetic, +29% ResNet-50 throughput) but
    batch STATISTICS must stay fp32-accumulated and fp32-stored — the
    bf16 model's logits and running stats must track an explicit
    fp32-norm twin within bf16 tolerance."""
    from flax import linen as nn

    from chainermn_tpu.models.resnet import ResNet18

    def fp32_norm(size, **kw):
        del size
        kw.pop("dtype", None)
        return nn.BatchNorm(
            use_running_average=kw.pop("use_running_average", None),
            momentum=0.9, epsilon=1e-5, dtype=jnp.float32, **kw,
        )

    x = jnp.asarray(
        np.random.RandomState(0).randn(4, 32, 32, 3), jnp.float32
    )
    bf16 = ResNet18(num_classes=5, train=True)  # default: bf16 BN
    fp32 = ResNet18(num_classes=5, train=True, norm=fp32_norm)
    v_bf = bf16.init(jax.random.PRNGKey(0), x[:1])
    v_fp = fp32.init(jax.random.PRNGKey(0), x[:1])
    # identical param trees (dtype is arithmetic-only, not storage)
    chex_equal = jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: bool(np.allclose(np.asarray(a), np.asarray(b))),
        v_bf["params"], v_fp["params"],
    ))
    assert chex_equal
    out_bf, mut_bf = bf16.apply(v_bf, x, mutable=["batch_stats"])
    out_fp, mut_fp = fp32.apply(v_fp, x, mutable=["batch_stats"])
    np.testing.assert_allclose(
        np.asarray(out_bf), np.asarray(out_fp), atol=0.15, rtol=0.1
    )
    # running stats: stored fp32, numerically matching the fp32 twin
    for leaf_bf, leaf_fp in zip(
        jax.tree_util.tree_leaves(mut_bf["batch_stats"]),
        jax.tree_util.tree_leaves(mut_fp["batch_stats"]),
    ):
        assert leaf_bf.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(leaf_bf), np.asarray(leaf_fp), atol=2e-2
        )


def test_dropout_is_train_gated():
    model = models.AlexNet(num_classes=5, train=True)
    variables, _ = _init_and_forward(model)
    x = jnp.ones((4, IMG, IMG, 3))
    a = model.apply(variables, x, rngs={"dropout": jax.random.PRNGKey(3)})
    b = model.apply(variables, x, rngs={"dropout": jax.random.PRNGKey(4)})
    assert not np.allclose(np.asarray(a), np.asarray(b))
    det = models.AlexNet(num_classes=5, train=False)
    c = det.apply(variables, x)
    d = det.apply(variables, x)
    np.testing.assert_allclose(np.asarray(c), np.asarray(d))


class TestHasAuxTrainStep:
    """build_train_step(has_aux=True): BN stats flow through the step and
    are mean-reduced across the mesh."""

    @pytest.fixture(scope="class")
    def comm(self, devices8):
        return cmn.create_communicator("tpu", devices=devices8)

    def test_batch_stats_updated_and_replicated(self, comm):
        model = models.ResNet18(num_classes=4, dtype=jnp.float32, train=True)
        x0 = jnp.zeros((1, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x0)
        params = {"params": variables["params"],
                  "batch_stats": variables["batch_stats"]}
        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)

        def loss_fn(p, batch):
            x, y = batch
            out, mut = model.apply(
                {"params": p["params"], "batch_stats": p["batch_stats"]},
                x, mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                out, y
            ).mean()
            return loss, mut["batch_stats"]

        step = build_train_step(
            comm, loss_fn, opt, has_aux=True, donate=False,
            merge_aux=lambda p, aux: {**p, "batch_stats": aux},
        )
        params, opt_state = step.place(params, opt.init(params))
        old_stats = jax.tree_util.tree_map(
            np.asarray, jax.device_get(params["batch_stats"])
        )
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
        y = jnp.arange(8, dtype=jnp.int32) % 4
        new_params, _, metrics = step(params, opt_state, (x, y))
        new_stats = jax.device_get(new_params["batch_stats"])
        # Stats moved (momentum update happened)
        changed = jax.tree_util.tree_map(
            lambda a, b: not np.allclose(a, b), old_stats, new_stats
        )
        assert any(jax.tree_util.tree_leaves(changed))
        assert np.isfinite(float(metrics["loss"]))

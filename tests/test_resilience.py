"""Resilience layer tests: fault injection, retry/backoff, the cross-rank
non-finite-step guard, and trainer auto-resume.

Strategy mirrors the suite's "real small world, no mocks" rule: every
recovery path runs against the real 8-device virtual CPU mesh (the
2-process ``jax.distributed`` variants live in ``test_multiprocess.py``,
scenario ``resilience``).  Injection is deterministic — (site, call
count) addressed, seeded — so each test asserts the exact sequence of
faults, retries, and recoveries.
"""

import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import chainermn_tpu as cmn
from chainermn_tpu.optimizers import build_train_step
from chainermn_tpu.training.trainer import Trainer, Updater
from chainermn_tpu.iterators import SerialIterator
from chainermn_tpu.resilience import (
    FaultInjector,
    FaultSpec,
    PayloadCorruptionError,
    ResilienceLog,
    RestartBudgetExceededError,
    RetryPolicy,
    StepDivergedError,
    TransientCommError,
    call_with_retry,
    inject_faults,
)
from chainermn_tpu.resilience import fault_injection as fi

from conftest import cpu_devices


@pytest.fixture(scope="module")
def comm():
    return cmn.create_communicator("flat", devices=cpu_devices(8))


# ----------------------------------------------------------------------
# Fault injector
# ----------------------------------------------------------------------
class TestFaultInjector:
    def test_off_by_default_noop_fast_path(self):
        assert fi.active() is None
        payload = b"untouched"
        assert fi.fire("anything", payload=payload) is payload

    def test_call_count_addressing(self):
        inj = FaultInjector([FaultSpec("s", "timeout", at=[2, 4])])
        inj.fire("s")  # call 1: clean
        with pytest.raises(TransientCommError):
            inj.fire("s")  # call 2: fires
        inj.fire("s")  # call 3: clean
        with pytest.raises(TransientCommError):
            inj.fire("s")  # call 4: fires
        assert inj.call_count("s") == 4
        assert len(inj.log.events("fault_injected")) == 2

    def test_sites_are_independent(self):
        inj = FaultInjector([FaultSpec("a", "timeout", at=[1])])
        inj.fire("b")  # other sites never trip the spec
        with pytest.raises(TransientCommError):
            inj.fire("a")

    def test_seeded_probability_is_deterministic(self):
        def pattern(seed):
            inj = FaultInjector(
                [FaultSpec("s", "timeout", probability=0.5)], seed=seed
            )
            out = []
            for _ in range(32):
                try:
                    inj.fire("s")
                    out.append(0)
                except TransientCommError:
                    out.append(1)
            return out

        assert pattern(7) == pattern(7)
        assert pattern(7) != pattern(8)  # seed actually matters
        assert sum(pattern(7)) > 0

    def test_max_fires_bounds_a_spec(self):
        inj = FaultInjector(
            [FaultSpec("s", "timeout", at=[1, 2, 3], max_fires=1)]
        )
        with pytest.raises(TransientCommError):
            inj.fire("s")
        inj.fire("s")  # budget spent: calls 2 and 3 pass
        inj.fire("s")

    def test_truncate_mutates_payload(self):
        inj = FaultInjector(
            [FaultSpec("s", "truncate", at=[1], truncate_to=3)]
        )
        assert inj.fire("s", payload=b"0123456789") == b"012"
        assert inj.fire("s", payload=b"0123456789") == b"0123456789"

    def test_delay_sleeps(self):
        import time

        inj = FaultInjector([FaultSpec("s", "delay", at=[1], delay=0.2)])
        t0 = time.monotonic()
        inj.fire("s")
        assert time.monotonic() - t0 >= 0.15

    def test_context_manager_restores_previous(self):
        assert fi.active() is None
        with inject_faults([FaultSpec("x", "timeout", at=[1])]) as outer:
            assert fi.active() is outer
            with inject_faults([]) as inner:
                assert fi.active() is inner
            assert fi.active() is outer
        assert fi.active() is None

    def test_env_activation_and_die(self, tmp_path):
        """The env-var path (how spawned mp workers are injected) and the
        simulated-process-death kind, in a throwaway subprocess."""
        import json

        code = (
            "from chainermn_tpu.resilience import fault_injection as fi\n"
            "assert fi.active() is not None\n"
            "fi.fire('warm')\n"          # other sites unaffected
            "fi.fire('doom')\n"          # call 1: clean
            "fi.fire('doom')\n"          # call 2: dies with code 43
            "print('UNREACHABLE')\n"
        )
        from conftest import subprocess_env

        env = subprocess_env(1)
        env[fi.ENV_SPEC] = json.dumps(
            [{"site": "doom", "kind": "die", "at": [2], "exit_code": 43}]
        )
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 43, r.stderr
        assert "UNREACHABLE" not in r.stdout

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            FaultSpec("s", "explode")


# ----------------------------------------------------------------------
# Retry / backoff
# ----------------------------------------------------------------------
class TestRetry:
    def test_backoff_schedule_is_deterministic(self):
        p = RetryPolicy(max_attempts=5, base_delay=0.1, multiplier=2.0,
                        max_delay=0.5)
        assert p.schedule() == [0.1, 0.2, 0.4, 0.5]

    def test_absorbs_transient_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise TimeoutError("slow peer")
            return "ok"

        log = ResilienceLog()
        from chainermn_tpu.resilience import log as rlog

        rlog.attach(log)
        try:
            out = call_with_retry(
                flaky, site="t", policy=RetryPolicy(4, base_delay=0.0)
            )
        finally:
            rlog.detach(log)
        assert out == "ok" and len(calls) == 3
        assert len(log.events("retry")) == 2

    def test_exhaustion_raises_with_diagnostics(self):
        def always():
            raise TimeoutError("never")

        with pytest.raises(TransientCommError) as ei:
            call_with_retry(always, site="s", peer=3,
                            policy=RetryPolicy(3, base_delay=0.0))
        e = ei.value
        assert e.recoverable
        assert e.site == "s" and e.peer == 3 and e.attempts == 3
        assert e.elapsed is not None
        assert "3 attempts" in str(e) and "peer=3" in str(e)

    def test_unclassified_error_propagates_immediately(self):
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("not transient")

        with pytest.raises(ValueError):
            call_with_retry(broken, site="s",
                            policy=RetryPolicy(4, base_delay=0.0))
        assert len(calls) == 1  # no blind retries of unknown failures

    def test_jax_deadline_text_is_transient(self):
        from chainermn_tpu.resilience.retry import is_transient

        assert is_transient(RuntimeError("DEADLINE_EXCEEDED: kv get"))
        assert not is_transient(RuntimeError("INVALID_ARGUMENT"))


# ----------------------------------------------------------------------
# Obj store + collectives under injection (8-rank single controller)
# ----------------------------------------------------------------------
class TestObjStoreResilience:
    def test_transient_recv_timeout_is_retried(self, comm):
        with inject_faults(
            [FaultSpec("obj_store.recv", "timeout", at=[1])]
        ) as inj:
            comm.send_obj({"x": 1}, dest=2, tag=9)
            assert comm.recv_obj(source=-1, tag=9, dest=2) == {"x": 1}
        assert len(inj.log.events("fault_injected")) == 1

    def test_retry_exhaustion_names_site_and_attempts(self, comm):
        with inject_faults(
            [FaultSpec("obj_store.recv", "timeout", at=[1, 2, 3, 4])]
        ):
            comm.send_obj("y", dest=0, tag=3)
            with pytest.raises(TransientCommError) as ei:
                comm.recv_obj(source=-1, tag=3, dest=0)
        assert ei.value.site == "obj_store.recv"
        assert ei.value.attempts == 4

    def test_truncated_payload_is_classified(self, comm):
        with inject_faults(
            [FaultSpec("obj_store.send", "truncate", at=[1])]
        ):
            comm.send_obj({"big": list(range(1000))}, dest=1, tag=4)
            with pytest.raises(PayloadCorruptionError) as ei:
                comm.recv_obj(source=-1, tag=4, dest=1)
        assert ei.value.recoverable

    def test_bcast_obj_timeout_retried(self, comm):
        with inject_faults(
            [FaultSpec("obj_store.exchange", "timeout", at=[1])]
        ):
            assert comm.bcast_obj("payload") == "payload"

    def test_barrier_timeout_retried(self, comm):
        with inject_faults([FaultSpec("barrier", "timeout", at=[1])]) as inj:
            comm.barrier()
        assert inj.call_count("barrier") == 2  # fault + clean retry


class TestCollectiveInjection:
    def test_allreduce_timeout_retried_result_correct(self, comm):
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        with inject_faults(
            [FaultSpec("collective.allreduce", "timeout", at=[1])]
        ) as inj:
            out = np.asarray(comm.allreduce(x, op="sum"))
        np.testing.assert_allclose(out, np.full((8, 1), 28.0))
        assert len(inj.log.events("fault_injected")) == 1

    def test_unclassified_collective_error_propagates(self, comm):
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        with inject_faults(
            [FaultSpec("collective.allgather", "error", at=[1])]
        ):
            with pytest.raises(RuntimeError, match="injected error"):
                comm.allgather(x)

    def test_no_injector_no_interference(self, comm):
        # the same calls with the injector inactive (the hot path)
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        np.testing.assert_allclose(
            np.asarray(comm.allreduce(x, op="sum")),
            np.full((8, 1), 28.0),
        )


# ----------------------------------------------------------------------
# Cross-rank non-finite step guard (8-device virtual mesh)
# ----------------------------------------------------------------------
def _guard_pieces(comm, nonfinite):
    lr = 0.1

    def loss_fn(params, batch):
        return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

    opt = cmn.create_multi_node_optimizer(optax.sgd(lr), comm)
    step = build_train_step(comm, loss_fn, opt, donate=False,
                            nonfinite=nonfinite)
    params, opt_state = step.place(
        {"w": jnp.zeros((4,))}, opt.init({"w": jnp.zeros((4,))})
    )
    rows = np.stack(
        [np.full((4,), float(i), np.float32) for i in range(comm.size)]
    )
    bad = rows.copy()
    bad[3, 2] = np.nan  # non-finite on ONE shard of the mesh

    def w_at(k):  # closed form from w0 = 0
        c = float(np.mean(np.arange(comm.size)))
        return c * (1.0 - (1.0 - lr) ** k)

    return step, params, opt_state, rows, bad, w_at


class TestNonfiniteStepGuard:
    def test_skip_is_agreed_and_params_roll_forward(self, comm):
        step, params, opt_state, rows, bad, w_at = _guard_pieces(
            comm, "skip"
        )
        params, opt_state, m1 = step(params, opt_state, rows)
        assert float(m1["grads_finite"]) == 1.0
        params, opt_state, m2 = step(params, opt_state, bad)
        assert float(m2["grads_finite"]) == 0.0
        np.testing.assert_allclose(  # NaN step skipped on EVERY rank
            np.asarray(params["w"]), np.full((4,), w_at(1)), rtol=1e-6
        )
        params, opt_state, m3 = step(params, opt_state, rows)
        assert float(m3["grads_finite"]) == 1.0
        np.testing.assert_allclose(  # training continued cleanly
            np.asarray(params["w"]), np.full((4,), w_at(2)), rtol=1e-6
        )
        assert not np.isnan(np.asarray(params["w"])).any()

    def test_warn_policy_applies_the_step(self, comm):
        step, params, opt_state, rows, bad, _ = _guard_pieces(
            comm, "warn"
        )
        params, opt_state, m = step(params, opt_state, bad)
        assert float(m["grads_finite"]) == 0.0
        assert np.isnan(np.asarray(params["w"])).any()

    def test_guard_off_means_no_metric(self, comm):
        step, params, opt_state, rows, _, _ = _guard_pieces(comm, None)
        _, _, m = step(params, opt_state, rows)
        assert "grads_finite" not in m
        assert step.nonfinite_policy is None

    def test_invalid_policy_rejected(self, comm):
        def loss_fn(params, batch):
            return jnp.sum(params["w"] * batch.mean())

        opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
        with pytest.raises(ValueError, match="nonfinite"):
            build_train_step(comm, loss_fn, opt, nonfinite="explode")


# ----------------------------------------------------------------------
# Trainer: policy host side + auto-resume
# ----------------------------------------------------------------------
def _make_trainer(comm, tmp, *, nonfinite=None, batches=None,
                  stop=(6, "iteration"), ckpt_name="rckpt"):
    lr = 0.1

    def loss_fn(params, batch):
        return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

    opt = cmn.create_multi_node_optimizer(optax.sgd(lr), comm)
    step = build_train_step(comm, loss_fn, opt, donate=False,
                            nonfinite=nonfinite)
    params, opt_state = step.place(
        {"w": jnp.zeros((4,))}, opt.init({"w": jnp.zeros((4,))})
    )
    if batches is None:
        batches = [np.full((4,), float(i), np.float32)
                   for i in range(comm.size)]
    it = SerialIterator(batches, comm.size, shuffle=False)
    trainer = Trainer(Updater(it, step, params, opt_state),
                      stop_trigger=stop)
    if tmp is not None:
        ckpt = cmn.create_multi_node_checkpointer(
            ckpt_name, comm, path=str(tmp)
        )
        trainer.extend(ckpt, trigger=(1, "iteration"))
    return trainer


class TestTrainerGuardPolicies:
    def test_skip_records_event(self, comm):
        tr = _make_trainer(comm, None, nonfinite="skip",
                           stop=(2, "iteration"))
        # iteration 2's batch carries a NaN
        bad = [np.full((4,), 1.0, np.float32) for _ in range(comm.size)]
        bad[0] = np.full((4,), np.nan, np.float32)
        tr.updater.iterator = SerialIterator(
            [np.full((4,), 1.0, np.float32)] * comm.size + bad,
            comm.size, shuffle=False,
        )
        tr.run()
        evs = tr.resilience_log.events("nonfinite_step")
        assert len(evs) == 1 and evs[0].info["iteration"] == 2

    def test_abort_raises_step_diverged(self, comm):
        tr = _make_trainer(comm, None, nonfinite="abort",
                           stop=(2, "iteration"))
        bad = [np.full((4,), np.nan, np.float32)] * comm.size
        tr.updater.iterator = SerialIterator(bad, comm.size, shuffle=False)
        with pytest.raises(StepDivergedError):
            tr.run()
        assert not tr.resilience_log.events("restart")

    def test_abort_is_not_auto_resumed(self, comm, tmp_path):
        # StepDivergedError is non-recoverable: max_restarts must NOT
        # absorb it (restarting would diverge identically)
        tr = _make_trainer(comm, tmp_path, nonfinite="abort",
                           stop=(2, "iteration"))
        bad = [np.full((4,), np.nan, np.float32)] * comm.size
        tr.updater.iterator = SerialIterator(bad, comm.size, shuffle=False)
        with pytest.raises(StepDivergedError):
            tr.run(max_restarts=5)

    def test_warn_policy_warns(self, comm):
        tr = _make_trainer(comm, None, nonfinite="warn",
                           stop=(1, "iteration"))
        bad = [np.full((4,), np.nan, np.float32)] * comm.size
        tr.updater.iterator = SerialIterator(bad, comm.size, shuffle=False)
        with pytest.warns(UserWarning, match="non-finite"):
            tr.run()


class TestAutoResume:
    def test_transient_fault_resumes_and_matches_oracle(self, comm,
                                                        tmp_path):
        oracle = _make_trainer(comm, tmp_path / "a", ckpt_name="o")
        oracle.run()
        w_oracle = np.asarray(oracle.updater.params["w"]).copy()

        tr = _make_trainer(comm, tmp_path / "b")
        with inject_faults(
            [FaultSpec("trainer.update", "timeout", at=[4])]
        ):
            tr.run(max_restarts=2)
        assert tr.iteration == 6
        assert tr.restarts == 1
        np.testing.assert_allclose(
            np.asarray(tr.updater.params["w"]), w_oracle, rtol=1e-6
        )
        counts = tr.resilience_log.counts
        assert counts["restart"] == 1
        assert counts["fault_injected"] >= 1
        (restart,) = tr.resilience_log.events("restart")
        assert restart.info["restored_step"] == 3

    def test_budget_exhaustion_raises(self, comm, tmp_path):
        tr = _make_trainer(comm, tmp_path)
        with inject_faults(
            [FaultSpec("trainer.update", "timeout", at=[2, 3, 4, 5, 6])]
        ):
            with pytest.raises(RestartBudgetExceededError) as ei:
                tr.run(max_restarts=1)
        assert not ei.value.recoverable
        assert tr.restarts == 1  # budget spent before giving up
        assert isinstance(ei.value.__cause__, TransientCommError)

    def test_default_budget_is_zero(self, comm, tmp_path):
        # max_restarts=0 (default): auto-resume never engages, and the
        # ORIGINAL recoverable error propagates unchanged (pre-resilience
        # behavior) so outer layers can apply their own policy
        tr = _make_trainer(comm, tmp_path)
        with inject_faults(
            [FaultSpec("trainer.update", "timeout", at=[2])]
        ):
            with pytest.raises(TransientCommError):
                tr.run()
        assert tr.restarts == 0

    def test_resume_without_checkpointer_continues(self, comm):
        # no checkpointer extension: state is still consistent (the
        # faulted update never mutated params), so training continues
        # from the in-flight state rather than failing
        tr = _make_trainer(comm, None)
        with inject_faults(
            [FaultSpec("trainer.update", "timeout", at=[3])]
        ):
            tr.run(max_restarts=1)
        assert tr.iteration == 6
        (restart,) = tr.resilience_log.events("restart")
        assert restart.info["restored_step"] is None

    def test_corruption_is_recoverable_end_to_end(self, comm, tmp_path):
        # a truncated control-plane payload inside an update surfaces as
        # PayloadCorruptionError (recoverable) and auto-resume absorbs it
        tr = _make_trainer(comm, tmp_path)
        orig_update = tr.updater.update.__func__

        def update_with_exchange(self_):
            # an obj exchange rides along with the update; call 4's send
            # is truncated by the spec below
            tr2 = getattr(self_, "_exchange_count", 0) + 1
            self_._exchange_count = tr2
            comm.send_obj({"hb": tr2}, dest=0, tag=77)
            comm.recv_obj(source=-1, tag=77, dest=0)
            orig_update(self_)

        tr.updater.update = update_with_exchange.__get__(tr.updater)
        with inject_faults(
            [FaultSpec("obj_store.send", "truncate", at=[4])]
        ):
            tr.run(max_restarts=1)
        assert tr.iteration == 6
        assert tr.restarts == 1


class TestEvaluatorReporting:
    def test_resilience_counts_surface_in_observation(self, comm,
                                                      tmp_path):
        from chainermn_tpu.extensions.evaluator import Evaluator

        # NaN batch at iteration 2; the guard's deferred host read
        # consumes its flag during iteration 3, so the evaluator firing
        # at iteration 4 sees the counter
        tr = _make_trainer(comm, tmp_path, nonfinite="skip",
                           stop=(4, "iteration"))
        bad = [np.full((4,), 1.0, np.float32) for _ in range(comm.size)]
        bad[0] = np.full((4,), np.nan, np.float32)
        tr.updater.iterator = SerialIterator(
            [np.full((4,), 1.0, np.float32)] * comm.size + bad
            + [np.full((4,), 1.0, np.float32)] * (2 * comm.size),
            comm.size, shuffle=False,
        )

        def metric_fn(params, batch):
            return {"zero": jnp.mean(batch) * 0.0}

        ev = Evaluator(
            lambda: iter(
                [np.ones((comm.size, 4), np.float32)]
            ),
            metric_fn, comm,
        )
        tr.extend(ev, trigger=(4, "iteration"))
        tr.run()
        assert tr.observation["resilience/nonfinite_step"] == 1


class TestExceptHookTaxonomy:
    def test_hook_prints_structured_diagnostics(self):
        from conftest import subprocess_env

        code = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "import chainermn_tpu as cmn\n"
            "cmn.global_except_hook.add_hook()\n"
            "from chainermn_tpu.resilience import TransientCommError\n"
            "raise TransientCommError('boom', site='obj_store.recv',\n"
            "                         peer=1, attempts=4, elapsed=2.5)\n"
        )
        r = subprocess.run(
            [sys.executable, "-c", code], env=subprocess_env(1),
            capture_output=True, text=True, timeout=240,
        )
        assert r.returncode != 0
        assert "resilience: kind=TransientCommError" in r.stderr
        assert "site=obj_store.recv" in r.stderr
        assert "attempts=4" in r.stderr

"""Fleet chaos tier (ISSUE 14) — tier-1 coverage.

Three layers, cheap to expensive:

* **Harness units** (no processes): the ``FaultSchedule`` DSL's
  compilation/composition/env rendering, ``FleetWorld``'s env wiring,
  and ``FleetReport``'s merge/dedupe/ordering contracts over
  synthesized artifacts.
* **Wide-world units** (no processes): the O(world) paths pinned at
  N=16/64 against mocked obj stores — ``newest_common_step`` election
  with a corrupt snapshot and a persistently slow rank, the
  leave-one-out straggler median with TWO simultaneous stragglers and
  a migrating one, ``scatter_dataset`` shard balance, and the
  16→12→14→8 ZeRO block-reshard chain's bit-identity.
* **One 8-process smoke** (``multiprocess`` mark, hard wall-clock
  budget — see tests/README.md): a preemption wave + one reshard leg
  through the real launcher, ending in the merged report's
  fault→retry→reform→reshard→resume order assertion.  The 16-64-rank
  scenarios live in test_fleet_chaos.py behind the ``slow`` mark.
"""

import json
import os

import numpy as np
import pytest

from chainermn_tpu.fleet import (
    ChainLeg,
    ElasticityChain,
    FaultSchedule,
    FleetBudgetError,
    FleetReport,
    FleetWorld,
    momentum_oracle,
)
from chainermn_tpu.fleet.schedule import ENV_SLICE
from chainermn_tpu.resilience.fault_injection import ENV_SPEC, FaultSpec


# ----------------------------------------------------------------------
class TestFaultScheduleDSL:
    def test_preemption_wave_spreads_deterministically(self):
        s = FaultSchedule().preemption_wave((3, 5, 9, 11), window=(4, 7))
        specs = s.specs()
        assert [d["process"] for d in specs] == [3, 5, 9, 11]
        assert all(d["kind"] == "die" for d in specs)
        # evenly spread over the window, deterministic by position
        assert [d["at"] for d in specs] == [[4], [5], [6], [7]]
        # byte-identical compilation on a rebuild
        s2 = FaultSchedule().preemption_wave((3, 5, 9, 11), window=(4, 7))
        assert s2.env() == s.env()

    def test_one_call_window_is_a_simultaneous_wave(self):
        s = FaultSchedule().preemption_wave((1, 2), window=(3, 3),
                                            exit_code=44)
        assert [d["at"] for d in s.specs()] == [[3], [3]]
        assert all(d["exit_code"] == 44 for d in s.specs())

    def test_slice_loss_targets_the_whole_slice_and_exports_grouping(self):
        s = FaultSchedule().slice_loss(1, slice_size=4, at=2)
        assert [d["process"] for d in s.specs()] == [4, 5, 6, 7]
        env = s.env()
        assert env[ENV_SLICE] == "4"
        # the rendered payload round-trips through the injector's own
        # constructor (what the spawned worker's _from_env does)
        specs = [FaultSpec(**d) for d in json.loads(env[ENV_SPEC])]
        assert all(sp.kind == "die" for sp in specs)

    def test_conflicting_slice_groupings_refused(self):
        s = FaultSchedule().slice_loss(0, slice_size=4, at=1)
        with pytest.raises(ValueError, match="one slice grouping"):
            s.slice_loss(1, slice_size=8, at=2)
        other = FaultSchedule().slice_loss(0, slice_size=8, at=1)
        with pytest.raises(ValueError, match="cannot compose"):
            s.compose(other)

    def test_migrating_straggler_two_windows(self):
        s = (FaultSchedule()
             .straggler(3, window=(1, 4), delay=0.2)
             .straggler(9, window=(5, 8), delay=0.2))
        specs = s.specs()
        assert specs[0]["process"] == 3 and specs[0]["at"] == [1, 2, 3, 4]
        assert specs[1]["process"] == 9 and specs[1]["at"] == [5, 6, 7, 8]

    def test_torn_payload_and_compose(self):
        a = FaultSchedule().torn_payload(calls=(1, 3), truncate_to=4)
        b = FaultSchedule().preemption_wave((2,), window=(5, 5))
        c = a.compose(b)
        assert len(c) == 3
        assert [d["kind"] for d in c.specs()] == ["truncate", "truncate",
                                                  "die"]
        # composition copies: mutating c never reaches a or b
        c.straggler(1, window=(1, 1))
        assert len(a) == 2 and len(b) == 1

    def test_validation_is_eager(self):
        with pytest.raises(ValueError):
            FaultSchedule().fault("site", "not_a_kind")
        with pytest.raises(ValueError, match="window"):
            FaultSchedule().straggler(0, window=(3, 2))
        with pytest.raises(ValueError, match="duplicate"):
            FaultSchedule().preemption_wave((1, 1), window=(1, 1))
        with pytest.raises(ValueError, match="at least one"):
            FaultSchedule().preemption_wave((), window=(1, 1))


class TestFleetWorldEnvWiring:
    def test_env_for_wires_schedule_and_targeting(self, tmp_path):
        sched = FaultSchedule(seed=7).slice_loss(0, slice_size=2, at=1)
        w = FleetWorld(4, tmp_path, local_devices=2, schedule=sched)
        env = w.env_for(3)
        assert env["CHAINERMN_TPU_FAULT_PROCESS_INDEX"] == "3"
        assert env["CHAINERMN_TPU_FAULT_SEED"] == "7"
        # 2 processes/slice x 2 devices/process: the exported topology
        # grouping counts device positions
        assert env[ENV_SLICE] == "4"
        assert "device_count=2" in env["XLA_FLAGS"]
        assert "JAX_PLATFORMS" not in env
        assert json.loads(env[ENV_SPEC]) == sched.specs()

    def test_slice_grouping_scales_with_local_devices(self, tmp_path):
        # slice_size counts PROCESSES; the topology env knob counts
        # device positions — env_for reconciles the units so both
        # groupings always name the same process sets
        sched = FaultSchedule().slice_loss(0, slice_size=2, at=1)
        w = FleetWorld(8, tmp_path, local_devices=2, schedule=sched)
        assert w.env_for(0)[ENV_SLICE] == "4"
        # one device per process: exported verbatim
        w1 = FleetWorld(8, tmp_path, schedule=sched)
        assert w1.env_for(0)[ENV_SLICE] == "2"

    def test_rejects_empty_world(self, tmp_path):
        with pytest.raises(ValueError):
            FleetWorld(0, tmp_path)


# ----------------------------------------------------------------------
# wide-world unit coverage (satellites): the O(world) paths at N=64,
# no processes
# ----------------------------------------------------------------------
class _WideObjComm:
    """A mocked 64-process obj store for the election paths: this rank's
    inventory is live, the other 63 are scripted; the first
    ``flaky_attempts`` exchanges fail the way a persistently slow (or
    torn) rank fails, exercising the lockstep retry."""

    def __init__(self, peer_inventories, process_index=0,
                 flaky_attempts=0, flaky_exc=None):
        from chainermn_tpu.resilience.errors import TransientCommError

        self.process_count = len(peer_inventories) + 1
        self.process_index = process_index
        self.size = self.process_count
        self._peers = peer_inventories
        self._flaky = flaky_attempts
        self._exc = flaky_exc or TransientCommError(
            "rank 7 persistently slow: exchange deadline exceeded",
            site="obj_store.exchange",
        )
        self.exchanges = 0

    def allgather_obj(self, local):
        self.exchanges += 1
        if self._flaky > 0:
            self._flaky -= 1
            raise self._exc
        out = list(self._peers)
        out.insert(self.process_index, local)
        return out


def _local_steps(ckpt, steps, corrupt=()):
    """Materialize npz-tier snapshots on this rank's disk; ``corrupt``
    steps get a manifest whose digest can never match (the torn-write
    case the inventory must exclude)."""
    from chainermn_tpu.resilience import elastic

    for s in steps:
        d = ckpt._step_dir(s)
        os.makedirs(d, exist_ok=True)
        if s in corrupt:
            with open(os.path.join(d, "state.npz"), "wb") as f:
                f.write(b"torn")
            elastic.write_manifest(
                {"format": 1, "world_size": 64,
                 "files": {"state.npz": {"bytes": 4, "sha256": "0" * 64}}},
                os.path.join(d, elastic.MANIFEST_NAME),
            )


class TestWideWorldElection:
    """Satellite: ``newest_common_step`` + the lockstep-retried
    inventory allgather at N=64 (scenario shape: one rank holds a
    corrupt snapshot, one rank is persistently slow)."""

    def _ckpt(self, tmp_path, comm):
        from chainermn_tpu.extensions.checkpoint import (
            _MultiNodeCheckpointer,
        )

        return _MultiNodeCheckpointer(
            "wide", comm, path=str(tmp_path), use_orbax=False
        )

    def test_corrupt_snapshot_excluded_and_election_degrades(
        self, tmp_path
    ):
        # 63 peers all hold {1, 2, 3}; THIS rank's step 3 is torn, so
        # its inventory is {1, 2} and the 64-way election must land on
        # 2 — not raise at load time on the corrupt 3
        comm = _WideObjComm([[1, 2, 3]] * 63)
        ckpt = self._ckpt(tmp_path, comm)
        _local_steps(ckpt, (1, 2, 3), corrupt=(3,))
        assert ckpt._available_steps() == [1, 2]
        assert ckpt.newest_common_step() == 2

    def test_persistently_slow_rank_retried_in_lockstep(self, tmp_path):
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        comm = _WideObjComm([[1, 2]] * 63, flaky_attempts=2)
        ckpt = self._ckpt(tmp_path, comm)
        _local_steps(ckpt, (1, 2))
        slog = ResilienceLog()
        attach(slog)
        try:
            assert ckpt.newest_common_step() == 2
        finally:
            detach(slog)
        # two failed exchanges, each retried, third succeeds
        assert slog.counts.get("retry") == 2
        assert comm.exchanges == 3

    def test_torn_inventory_payload_retried(self, tmp_path):
        from chainermn_tpu.resilience.errors import PayloadCorruptionError

        comm = _WideObjComm(
            [[5]] * 63, flaky_attempts=1,
            flaky_exc=PayloadCorruptionError(
                "inventory payload failed to unpickle",
                site="obj_store.exchange",
            ),
        )
        ckpt = self._ckpt(tmp_path, comm)
        _local_steps(ckpt, (5,))
        assert ckpt.newest_common_step() == 5
        assert comm.exchanges == 2

    def test_one_empty_rank_elects_nothing(self, tmp_path):
        # a freshly joined rank with no snapshots: the 64-way common
        # set is empty and the election answers None (resume from
        # scratch), not a crash
        comm = _WideObjComm([[1, 2, 3]] * 62 + [[]])
        ckpt = self._ckpt(tmp_path, comm)
        _local_steps(ckpt, (1, 2, 3))
        assert ckpt.newest_common_step() is None


class _FakeTrainer:
    iteration = 16


def _phase_data(n, stragglers, *, straggler_host=0.3, healthy_host=0.01,
                step=1.0):
    by_proc = {}
    for p in range(n):
        host = straggler_host if p in stragglers else healthy_host
        by_proc[p] = {
            "process": p,
            "phases": {
                "step": [step] * 3,
                "update.host": [host] * 3,
            },
        }
    return by_proc


class TestWideStragglers:
    """Satellite: the leave-one-out straggler median at N=16/64 with
    TWO simultaneous stragglers, plus migration between windows."""

    def _report(self):
        from chainermn_tpu.observability import MetricsReport

        return MetricsReport(None, filename=None)

    @pytest.mark.parametrize("n", [16, 64])
    def test_two_simultaneous_stragglers_both_convicted(self, n):
        rep = self._report()
        rep._flag_stragglers(_phase_data(n, {3, 9}), _FakeTrainer())
        assert rep.straggler_processes == [3, 9]

    @pytest.mark.parametrize("n", [16, 64])
    def test_no_false_positives_on_healthy_world(self, n):
        rep = self._report()
        rep._flag_stragglers(_phase_data(n, set()), _FakeTrainer())
        assert rep.straggler_processes == []

    def test_straggler_migrates_between_windows(self):
        # window 1 convicts rank 3; window 2 (fresh samples — the
        # incremental-window contract) convicts rank 9 and NOT the
        # recovered rank 3
        rep = self._report()
        rep._flag_stragglers(_phase_data(16, {3}), _FakeTrainer())
        assert rep.straggler_processes == [3]
        rep._flag_stragglers(_phase_data(16, {9}), _FakeTrainer())
        assert rep.straggler_processes == [9]

    def test_materiality_floor_holds_at_64(self):
        # a "straggler" whose host phase is noise (way below the 5%
        # step floor) must not be convicted, even at ratio 30x
        rep = self._report()
        by_proc = _phase_data(64, {5}, straggler_host=0.03,
                              healthy_host=0.001, step=10.0)
        rep._flag_stragglers(by_proc, _FakeTrainer())
        assert rep.straggler_processes == []


class TestScatterShardBalance64:
    """Satellite: ``scatter_dataset`` shard balance at N=64 — the
    substrate a straggler-adaptive rebalance will skew."""

    def test_remainder_distribution_pattern_pinned(self):
        from chainermn_tpu.datasets.scatter_dataset import scatter_index

        n, size = 1000, 64  # 1000 = 64*15 + 40
        sizes, covered = [], []
        for r in range(size):
            order, start, end = scatter_index(n, size, r, equalize=False)
            sizes.append(end - start)
            covered.extend(order[start:end])
        # the first `rem` ranks absorb the remainder, one sample each
        assert sizes == [16] * 40 + [15] * 24
        # disjoint exact cover
        assert sorted(covered) == list(range(n))

    def test_equalized_shards_wrap_and_stay_balanced(self):
        from chainermn_tpu.datasets.scatter_dataset import scatter_index

        n, size = 1000, 64
        sizes, covered = [], []
        for r in range(size):
            order, start, end = scatter_index(n, size, r, equalize=True)
            sizes.append(end - start)
            covered.extend(order[start:end])
        # every rank steps the same number of times per epoch
        assert sizes == [16] * 64
        counts = np.bincount(np.asarray(covered), minlength=n)
        # the wrap-around pad re-serves exactly the first 24 samples
        assert list(counts[:24]) == [2] * 24
        assert list(counts[24:]) == [1] * (n - 24)


class TestChainReshardBitIdentity:
    """Satellite/tentpole contract: the 16→12→14→8 ZeRO block-reshard
    CHAIN is bit-identical to a fresh partition of the global state at
    every leg — composition introduces no drift."""

    @staticmethod
    def _fresh(flat, world):
        k = -(-flat.size // world)  # ceil
        out = np.zeros(world * k, flat.dtype)
        out[: flat.size] = flat
        return out.reshape(world, k)

    def test_chain_16_12_14_8_bit_identical_at_every_leg(self):
        from chainermn_tpu.resilience.elastic import reshard_blocked_leaf

        rng = np.random.RandomState(0)
        flat = rng.randn(1003).astype(np.float32)  # indivisible on purpose
        state = self._fresh(flat, 16)
        for world in (12, 14, 8):
            want = self._fresh(flat, world)
            state = reshard_blocked_leaf(state, want.shape)
            np.testing.assert_array_equal(state, want)

    def test_momentum_oracle_matches_closed_form_sgd(self):
        # mom=0 collapses to plain sgd's closed form — the oracle's own
        # sanity pin
        traj = momentum_oracle(5, lr=0.1, mom=0.0, c=0.5, dim=3)
        for k, w in enumerate(traj, start=1):
            np.testing.assert_allclose(
                w, 0.5 * (1 - 0.9 ** k) * np.ones(3), rtol=1e-12
            )


# ----------------------------------------------------------------------
class TestFleetReportMerge:
    def _write_events(self, path, rows):
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    def _ev(self, kind, t, process=0, site="s", **info):
        return {"kind": kind, "site": site, "process": process,
                "time": t, "monotonic": t, "info": info}

    def test_merge_orders_across_legs_and_processes(self, tmp_path):
        self._write_events(tmp_path / "leg0_p1_events.jsonl", [
            self._ev("fault_injected", 10.0, process=1, fault="die"),
        ])
        self._write_events(tmp_path / "leg1_p0_events.jsonl", [
            self._ev("world_reformed", 20.0),
            self._ev("elastic_reshard", 21.0),
        ])
        self._write_events(tmp_path / "leg1_p0_trainer_events.jsonl", [
            self._ev("elastic_reshard", 21.0),  # duplicate: deduped
            self._ev("elastic_restart", 22.0),
        ])
        rep = FleetReport.from_scratch(tmp_path)
        assert rep.counts == {
            "fault_injected": 1, "world_reformed": 1,
            "elastic_reshard": 1, "elastic_restart": 1,
        }
        rep.assert_order("fault_injected", "world_reformed",
                         "elastic_reshard", "elastic_restart")
        assert rep.processes == {"leg0": [1], "leg1": [0]}

    def test_order_violation_raises_with_post_mortem(self, tmp_path):
        self._write_events(tmp_path / "leg0_p0_events.jsonl", [
            self._ev("world_reformed", 5.0),
            self._ev("fault_injected", 9.0),
        ])
        rep = FleetReport.from_scratch(tmp_path)
        with pytest.raises(AssertionError, match="does not precede"):
            rep.assert_order("fault_injected", "world_reformed")
        with pytest.raises(AssertionError, match="no 'retry' event"):
            rep.assert_order("retry")

    def test_trace_spans_anchor_on_wall0_and_torn_tail_skipped(
        self, tmp_path
    ):
        with open(tmp_path / "leg0_p0_trace.jsonl", "w") as f:
            f.write(json.dumps({
                "type": "meta", "name": "timeline.meta", "t": 0.0,
                "process": 0, "tid": 0, "args": {"wall0": 100.0},
            }) + "\n")
            f.write(json.dumps({
                "type": "span", "name": "step", "t": 2.5, "dur": 0.1,
                "process": 0, "tid": 0, "args": {},
            }) + "\n")
            f.write('{"type": "span", "name": "torn')  # killed mid-write
        self._write_events(tmp_path / "leg0_p0_events.jsonl", [
            self._ev("fault_injected", 101.0),
        ])
        rep = FleetReport.from_scratch(tmp_path)
        spans = rep.events("span:step")
        assert len(spans) == 1 and spans[0]["wall"] == 102.5
        # the span slots in between on the shared wall clock
        rep.assert_order("fault_injected", "span:step")

    def test_timeline_meta_row_export(self, tmp_path):
        from chainermn_tpu.observability.timeline import Timeline

        tl = Timeline(label="x")
        with tl.span("work"):
            pass
        path = tl.to_jsonl(str(tmp_path / "t.jsonl"), meta=True)
        rows = [json.loads(l) for l in open(path)]
        assert rows[0]["type"] == "meta"
        assert rows[0]["args"]["wall0"] == tl.wall0
        assert [r["name"] for r in rows[1:]] == ["work"]
        # default export unchanged: no meta row
        path2 = tl.to_jsonl(str(tmp_path / "t2.jsonl"))
        rows2 = [json.loads(l) for l in open(path2)]
        assert all(r["type"] != "meta" for r in rows2)


class TestStreamingSink:
    def test_events_flushed_per_emit(self, tmp_path):
        from chainermn_tpu.resilience.log import (
            JsonlFileSink, attach, detach, emit,
        )

        sink = JsonlFileSink(str(tmp_path / "ev.jsonl"))
        attach(sink)
        try:
            emit("fault_injected", "site.a", fault="die", call=3)
            # on disk BEFORE any close/flush call — the os._exit case
            rows = [json.loads(l) for l in open(tmp_path / "ev.jsonl")]
        finally:
            detach(sink)
            sink.close()
        assert len(rows) == 1
        assert rows[0]["kind"] == "fault_injected"
        assert rows[0]["info"] == {"fault": "die", "call": 3}
        assert "monotonic" in rows[0] and "time" in rows[0]
        # the sink is still a queryable ResilienceLog
        assert sink.counts == {"fault_injected": 1}


# ----------------------------------------------------------------------
# process-spawning tier-1 pieces: the budget teardown and the 8-proc
# smoke of the full machinery (the 16+-rank worlds are `slow`)
# ----------------------------------------------------------------------
# hard wall-clock budget for the tier-1 smoke, documented in
# tests/README.md — the budget is a deadlock detector on a timeshared
# host, not a perf assertion
SMOKE_BUDGET_S = 240


@pytest.mark.multiprocess
class TestFleetWorldBudget:
    def test_overrun_tears_down_loudly(self, tmp_path):
        # the sleep scenario wedges unconditionally, so ANY budget
        # catches it — a small one keeps this tier-1 test cheap
        w = FleetWorld(1, tmp_path, budget_s=5, label="wedge")
        with pytest.raises(FleetBudgetError) as ei:
            w.launch("sleep", {"sleep_s": 3600})
        msg = str(ei.value)
        assert "exceeded its 5s wall-clock budget" in msg
        assert "process 0" in msg  # the tail is quoted


@pytest.mark.multiprocess
class TestFleetSmoke8:
    def test_wave_plus_reshard_8_to_6_on_oracle(self, tmp_path):
        """The tier-1 smoke of the full fleet machinery (ISSUE 14
        acceptance, 8-process shape): a torn rendezvous payload
        (lockstep-retried), a preemption wave killing processes 6 and 7
        at step 3, and one elasticity-chain leg resuming at world 6
        through the checkpoint resharder onto the single-world numpy
        oracle — with the merged FleetReport asserting the
        fault→retry→reform→reshard→resume event order.

        Also the regression test for the wide-world defect this
        scenario surfaced at 16 processes (and 2-process worlds never
        lost): the coordination service's peer-death propagation
        hard-aborts the wave's SURVIVORS, racing their epilogue.  The
        fix is epilogue-before-wave (worker.scenario_chain_leg) +
        REAPED acceptance (world.assert_ok) — every survivor's RESULT
        payload and streamed artifacts must exist despite any reap,
        and the resume leg must still find all of leg0's snapshots."""
        chain = ElasticityChain(str(tmp_path), [
            ChainLeg(n_procs=8, n_steps=3, wave_at=3,
                     wave_processes=(6, 7), torn_calls=(1,)),
            ChainLeg(n_procs=6, n_steps=5),
        ], budget_s=SMOKE_BUDGET_S)
        out = chain.run()
        legs = out["legs"]
        # every leg-0 process published its payload BEFORE the wave —
        # victims included (their RESULT precedes their die)
        assert sorted(legs[0]) == list(range(8))
        assert all(p["steps_saved"] == 2 for p in legs[0].values())
        assert sorted(legs[1]) == [0, 1, 2, 3, 4, 5]
        for p in legs[1].values():
            assert p["oracle_match"] is True
            assert p["resumed_step"] == 2
            assert p["resized"] == [8, 6]
            assert p["iteration"] == 5
        rep = out["report"]
        rep.assert_order("fault_injected", "retry", "world_reformed",
                         "elastic_reshard", "elastic_restart")
        # the wave's victims left their die records via the streaming
        # sink despite os._exit
        dies = [e for e in rep.events("fault_injected")
                if e["info"].get("fault") == "die"]
        assert sorted(e["process"] for e in dies) == [6, 7]
        assert all(e["leg"] == "leg0" for e in dies)
        # every leg-1 process re-agreed and resumed
        restarts = rep.events("elastic_restart")
        assert sorted(e["process"] for e in restarts) == [0, 1, 2, 3, 4, 5]

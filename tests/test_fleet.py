"""Fleet chaos tier (ISSUE 14) — tier-1 coverage.

Three layers, cheap to expensive:

* **Harness units** (no processes): the ``FaultSchedule`` DSL's
  compilation/composition/env rendering, ``FleetWorld``'s env wiring,
  and ``FleetReport``'s merge/dedupe/ordering contracts over
  synthesized artifacts.
* **Wide-world units** (no processes): the O(world) paths pinned at
  N=16/64 against mocked obj stores — ``newest_common_step`` election
  with a corrupt snapshot and a persistently slow rank, the
  leave-one-out straggler median with TWO simultaneous stragglers and
  a migrating one, ``scatter_dataset`` shard balance, and the
  16→12→14→8 ZeRO block-reshard chain's bit-identity.
* **One 8-process smoke** (``multiprocess`` mark, hard wall-clock
  budget — see tests/README.md): a preemption wave + one reshard leg
  through the real launcher, ending in the merged report's
  fault→retry→reform→reshard→resume order assertion.  The 16-64-rank
  scenarios live in test_fleet_chaos.py behind the ``slow`` mark.
"""

import json
import os

import numpy as np
import pytest

from chainermn_tpu.fleet import (
    ChainLeg,
    ElasticityChain,
    FaultSchedule,
    FleetBudgetError,
    FleetReport,
    FleetWorld,
    momentum_oracle,
)
from chainermn_tpu.fleet.schedule import ENV_SLICE
from chainermn_tpu.resilience.fault_injection import ENV_SPEC, FaultSpec


# ----------------------------------------------------------------------
class TestFaultScheduleDSL:
    def test_preemption_wave_spreads_deterministically(self):
        s = FaultSchedule().preemption_wave((3, 5, 9, 11), window=(4, 7))
        specs = s.specs()
        assert [d["process"] for d in specs] == [3, 5, 9, 11]
        assert all(d["kind"] == "die" for d in specs)
        # evenly spread over the window, deterministic by position
        assert [d["at"] for d in specs] == [[4], [5], [6], [7]]
        # byte-identical compilation on a rebuild
        s2 = FaultSchedule().preemption_wave((3, 5, 9, 11), window=(4, 7))
        assert s2.env() == s.env()

    def test_one_call_window_is_a_simultaneous_wave(self):
        s = FaultSchedule().preemption_wave((1, 2), window=(3, 3),
                                            exit_code=44)
        assert [d["at"] for d in s.specs()] == [[3], [3]]
        assert all(d["exit_code"] == 44 for d in s.specs())

    def test_slice_loss_targets_the_whole_slice_and_exports_grouping(self):
        s = FaultSchedule().slice_loss(1, slice_size=4, at=2)
        assert [d["process"] for d in s.specs()] == [4, 5, 6, 7]
        env = s.env()
        assert env[ENV_SLICE] == "4"
        # the rendered payload round-trips through the injector's own
        # constructor (what the spawned worker's _from_env does)
        specs = [FaultSpec(**d) for d in json.loads(env[ENV_SPEC])]
        assert all(sp.kind == "die" for sp in specs)

    def test_conflicting_slice_groupings_refused(self):
        s = FaultSchedule().slice_loss(0, slice_size=4, at=1)
        with pytest.raises(ValueError, match="one slice grouping"):
            s.slice_loss(1, slice_size=8, at=2)
        other = FaultSchedule().slice_loss(0, slice_size=8, at=1)
        with pytest.raises(ValueError, match="cannot compose"):
            s.compose(other)

    def test_migrating_straggler_two_windows(self):
        s = (FaultSchedule()
             .straggler(3, window=(1, 4), delay=0.2)
             .straggler(9, window=(5, 8), delay=0.2))
        specs = s.specs()
        assert specs[0]["process"] == 3 and specs[0]["at"] == [1, 2, 3, 4]
        assert specs[1]["process"] == 9 and specs[1]["at"] == [5, 6, 7, 8]

    def test_torn_payload_and_compose(self):
        a = FaultSchedule().torn_payload(calls=(1, 3), truncate_to=4)
        b = FaultSchedule().preemption_wave((2,), window=(5, 5))
        c = a.compose(b)
        assert len(c) == 3
        assert [d["kind"] for d in c.specs()] == ["truncate", "truncate",
                                                  "die"]
        # composition copies: mutating c never reaches a or b
        c.straggler(1, window=(1, 1))
        assert len(a) == 2 and len(b) == 1

    def test_validation_is_eager(self):
        with pytest.raises(ValueError):
            FaultSchedule().fault("site", "not_a_kind")
        with pytest.raises(ValueError, match="window"):
            FaultSchedule().straggler(0, window=(3, 2))
        with pytest.raises(ValueError, match="duplicate"):
            FaultSchedule().preemption_wave((1, 1), window=(1, 1))
        with pytest.raises(ValueError, match="at least one"):
            FaultSchedule().preemption_wave((), window=(1, 1))


class TestFleetWorldEnvWiring:
    def test_env_for_wires_schedule_and_targeting(self, tmp_path):
        sched = FaultSchedule(seed=7).slice_loss(0, slice_size=2, at=1)
        w = FleetWorld(4, tmp_path, local_devices=2, schedule=sched)
        env = w.env_for(3)
        assert env["CHAINERMN_TPU_FAULT_PROCESS_INDEX"] == "3"
        assert env["CHAINERMN_TPU_FAULT_SEED"] == "7"
        # 2 processes/slice x 2 devices/process: the exported topology
        # grouping counts device positions
        assert env[ENV_SLICE] == "4"
        assert "device_count=2" in env["XLA_FLAGS"]
        assert "JAX_PLATFORMS" not in env
        assert json.loads(env[ENV_SPEC]) == sched.specs()

    def test_slice_grouping_scales_with_local_devices(self, tmp_path):
        # slice_size counts PROCESSES; the topology env knob counts
        # device positions — env_for reconciles the units so both
        # groupings always name the same process sets
        sched = FaultSchedule().slice_loss(0, slice_size=2, at=1)
        w = FleetWorld(8, tmp_path, local_devices=2, schedule=sched)
        assert w.env_for(0)[ENV_SLICE] == "4"
        # one device per process: exported verbatim
        w1 = FleetWorld(8, tmp_path, schedule=sched)
        assert w1.env_for(0)[ENV_SLICE] == "2"

    def test_rejects_empty_world(self, tmp_path):
        with pytest.raises(ValueError):
            FleetWorld(0, tmp_path)


# ----------------------------------------------------------------------
# wide-world unit coverage (satellites): the O(world) paths at N=64,
# no processes
# ----------------------------------------------------------------------
class _WideObjComm:
    """A mocked 64-process obj store for the election paths: this rank's
    inventory is live, the other 63 are scripted; the first
    ``flaky_attempts`` exchanges fail the way a persistently slow (or
    torn) rank fails, exercising the lockstep retry."""

    def __init__(self, peer_inventories, process_index=0,
                 flaky_attempts=0, flaky_exc=None):
        from chainermn_tpu.resilience.errors import TransientCommError

        self.process_count = len(peer_inventories) + 1
        self.process_index = process_index
        self.size = self.process_count
        self._peers = peer_inventories
        self._flaky = flaky_attempts
        self._exc = flaky_exc or TransientCommError(
            "rank 7 persistently slow: exchange deadline exceeded",
            site="obj_store.exchange",
        )
        self.exchanges = 0

    def allgather_obj(self, local):
        self.exchanges += 1
        if self._flaky > 0:
            self._flaky -= 1
            raise self._exc
        out = list(self._peers)
        out.insert(self.process_index, local)
        return out


def _local_steps(ckpt, steps, corrupt=()):
    """Materialize npz-tier snapshots on this rank's disk; ``corrupt``
    steps get a manifest whose digest can never match (the torn-write
    case the inventory must exclude)."""
    from chainermn_tpu.resilience import elastic

    for s in steps:
        d = ckpt._step_dir(s)
        os.makedirs(d, exist_ok=True)
        if s in corrupt:
            with open(os.path.join(d, "state.npz"), "wb") as f:
                f.write(b"torn")
            elastic.write_manifest(
                {"format": 1, "world_size": 64,
                 "files": {"state.npz": {"bytes": 4, "sha256": "0" * 64}}},
                os.path.join(d, elastic.MANIFEST_NAME),
            )


class TestWideWorldElection:
    """Satellite: ``newest_common_step`` + the lockstep-retried
    inventory allgather at N=64 (scenario shape: one rank holds a
    corrupt snapshot, one rank is persistently slow)."""

    def _ckpt(self, tmp_path, comm):
        from chainermn_tpu.extensions.checkpoint import (
            _MultiNodeCheckpointer,
        )

        return _MultiNodeCheckpointer(
            "wide", comm, path=str(tmp_path), use_orbax=False
        )

    def test_corrupt_snapshot_excluded_and_election_degrades(
        self, tmp_path
    ):
        # 63 peers all hold {1, 2, 3}; THIS rank's step 3 is torn, so
        # its inventory is {1, 2} and the 64-way election must land on
        # 2 — not raise at load time on the corrupt 3
        comm = _WideObjComm([[1, 2, 3]] * 63)
        ckpt = self._ckpt(tmp_path, comm)
        _local_steps(ckpt, (1, 2, 3), corrupt=(3,))
        assert ckpt._available_steps() == [1, 2]
        assert ckpt.newest_common_step() == 2

    def test_persistently_slow_rank_retried_in_lockstep(self, tmp_path):
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        comm = _WideObjComm([[1, 2]] * 63, flaky_attempts=2)
        ckpt = self._ckpt(tmp_path, comm)
        _local_steps(ckpt, (1, 2))
        slog = ResilienceLog()
        attach(slog)
        try:
            assert ckpt.newest_common_step() == 2
        finally:
            detach(slog)
        # two failed exchanges, each retried, third succeeds
        assert slog.counts.get("retry") == 2
        assert comm.exchanges == 3

    def test_torn_inventory_payload_retried(self, tmp_path):
        from chainermn_tpu.resilience.errors import PayloadCorruptionError

        comm = _WideObjComm(
            [[5]] * 63, flaky_attempts=1,
            flaky_exc=PayloadCorruptionError(
                "inventory payload failed to unpickle",
                site="obj_store.exchange",
            ),
        )
        ckpt = self._ckpt(tmp_path, comm)
        _local_steps(ckpt, (5,))
        assert ckpt.newest_common_step() == 5
        assert comm.exchanges == 2

    def test_one_empty_rank_elects_nothing(self, tmp_path):
        # a freshly joined rank with no snapshots: the 64-way common
        # set is empty and the election answers None (resume from
        # scratch), not a crash
        comm = _WideObjComm([[1, 2, 3]] * 62 + [[]])
        ckpt = self._ckpt(tmp_path, comm)
        _local_steps(ckpt, (1, 2, 3))
        assert ckpt.newest_common_step() is None


class _FakeTrainer:
    iteration = 16


def _phase_data(n, stragglers, *, straggler_host=0.3, healthy_host=0.01,
                step=1.0):
    by_proc = {}
    for p in range(n):
        host = straggler_host if p in stragglers else healthy_host
        by_proc[p] = {
            "process": p,
            "phases": {
                "step": [step] * 3,
                "update.host": [host] * 3,
            },
        }
    return by_proc


class TestWideStragglers:
    """Satellite: the leave-one-out straggler median at N=16/64 with
    TWO simultaneous stragglers, plus migration between windows."""

    def _report(self):
        from chainermn_tpu.observability import MetricsReport

        return MetricsReport(None, filename=None)

    @pytest.mark.parametrize("n", [16, 64])
    def test_two_simultaneous_stragglers_both_convicted(self, n):
        rep = self._report()
        rep._flag_stragglers(_phase_data(n, {3, 9}), _FakeTrainer())
        assert rep.straggler_processes == [3, 9]

    @pytest.mark.parametrize("n", [16, 64])
    def test_no_false_positives_on_healthy_world(self, n):
        rep = self._report()
        rep._flag_stragglers(_phase_data(n, set()), _FakeTrainer())
        assert rep.straggler_processes == []

    def test_straggler_migrates_between_windows(self):
        # window 1 convicts rank 3; window 2 (fresh samples — the
        # incremental-window contract) convicts rank 9 and NOT the
        # recovered rank 3
        rep = self._report()
        rep._flag_stragglers(_phase_data(16, {3}), _FakeTrainer())
        assert rep.straggler_processes == [3]
        rep._flag_stragglers(_phase_data(16, {9}), _FakeTrainer())
        assert rep.straggler_processes == [9]

    def test_materiality_floor_holds_at_64(self):
        # a "straggler" whose host phase is noise (way below the 5%
        # step floor) must not be convicted, even at ratio 30x
        rep = self._report()
        by_proc = _phase_data(64, {5}, straggler_host=0.03,
                              healthy_host=0.001, step=10.0)
        rep._flag_stragglers(by_proc, _FakeTrainer())
        assert rep.straggler_processes == []


class TestScatterShardBalance64:
    """Satellite: ``scatter_dataset`` shard balance at N=64 — the
    substrate a straggler-adaptive rebalance will skew."""

    def test_remainder_distribution_pattern_pinned(self):
        from chainermn_tpu.datasets.scatter_dataset import scatter_index

        n, size = 1000, 64  # 1000 = 64*15 + 40
        sizes, covered = [], []
        for r in range(size):
            order, start, end = scatter_index(n, size, r, equalize=False)
            sizes.append(end - start)
            covered.extend(order[start:end])
        # the first `rem` ranks absorb the remainder, one sample each
        assert sizes == [16] * 40 + [15] * 24
        # disjoint exact cover
        assert sorted(covered) == list(range(n))

    def test_equalized_shards_wrap_and_stay_balanced(self):
        from chainermn_tpu.datasets.scatter_dataset import scatter_index

        n, size = 1000, 64
        sizes, covered = [], []
        for r in range(size):
            order, start, end = scatter_index(n, size, r, equalize=True)
            sizes.append(end - start)
            covered.extend(order[start:end])
        # every rank steps the same number of times per epoch
        assert sizes == [16] * 64
        counts = np.bincount(np.asarray(covered), minlength=n)
        # the wrap-around pad re-serves exactly the first 24 samples
        assert list(counts[:24]) == [2] * 24
        assert list(counts[24:]) == [1] * (n - 24)


class TestWeightedScatter:
    """Satellite (ISSUE 15): explicit per-rank ``scatter_dataset``
    weights with deterministic remainder placement — the shard map the
    adaptive rebalance skews — pinned at N=64 alongside the existing
    ``scatter_index`` remainder tests."""

    def test_equal_weights_reproduce_equalized_remainder_pattern(self):
        from chainermn_tpu.datasets import weighted_shard_counts
        from chainermn_tpu.datasets.scatter_dataset import scatter_index

        n, size = 1000, 64
        counts = weighted_shard_counts(n, [1.0] * size)
        legacy = []
        for r in range(size):
            _o, s, e = scatter_index(n, size, r, equalize=False)
            legacy.append(e - s)
        # ties in the largest-remainder placement break to the LOWER
        # rank, so equal weights reproduce the equalized split's
        # "first rem ranks absorb the remainder" exactly
        assert counts == legacy == [16] * 40 + [15] * 24

    def test_weighted_remainder_pattern_n64_pinned(self):
        from chainermn_tpu.datasets import weighted_shard_counts

        n, size = 1000, 64
        w = [1.0] * size
        w[5], w[9] = 0.5, 0.25
        counts = weighted_shard_counts(n, w)
        # deterministic largest-remainder placement: the two skewed
        # ranks take their quota floors, the last four full-weight
        # ranks lose the remainder — pinned exactly
        want = [16] * 64
        want[5], want[9] = 8, 4
        want[60:] = [15] * 4
        assert counts == want
        assert sum(counts) == n

    def test_equalized_weighted_split_uniform_width_full_cover(self):
        from chainermn_tpu.datasets.scatter_dataset import scatter_index

        n, size = 1000, 64
        w = [1.0] * size
        w[5], w[9] = 0.5, 0.25
        widths, covered = set(), set()
        for r in range(size):
            order, s, e = scatter_index(n, size, r, weights=w,
                                        equalize=True)
            widths.add(e - s)
            covered.update(int(i) for i in order[s:e])
        # every rank steps the same number of times per epoch (the
        # lockstep contract a rebalance must not break): short shards
        # wrap-pad WITHIN themselves to the widest shard
        assert widths == {16}
        assert covered == set(range(n))

    def test_unequalized_weighted_split_is_contiguous_and_disjoint(self):
        from chainermn_tpu.datasets.scatter_dataset import scatter_index

        n, size = 103, 8
        w = [1.0] * size
        w[3] = 0.2
        seen = []
        for r in range(size):
            order, s, e = scatter_index(n, size, r, weights=w,
                                        equalize=False)
            seen.extend(order[s:e])
        assert sorted(seen) == list(range(n))

    def test_min_count_lift_and_validation(self):
        from chainermn_tpu.datasets import weighted_shard_counts

        # a vanishing weight still gets >= 1 sample under min_count
        # (the equalized path's contract: np.resize of an empty shard
        # would fabricate indices) — stolen from the largest shard
        counts = weighted_shard_counts(10, [1.0, 1.0, 1e-9],
                                       min_count=1)
        assert counts == [4, 5, 1]
        assert sum(counts) == 10
        with pytest.raises(ValueError, match="finite and >= 0"):
            weighted_shard_counts(10, [1.0, -2.0])
        with pytest.raises(ValueError, match="at least one weight"):
            weighted_shard_counts(10, [0.0, 0.0])
        with pytest.raises(ValueError, match="cannot give"):
            weighted_shard_counts(3, [1.0] * 8, min_count=1)

    def test_explicit_zero_weight_is_a_probationary_rank(self):
        """Satellite (ISSUE 16): an EXPLICIT weight-0 rank owns no
        samples (probationary host), receives no remainder, is exempt
        from the min_count lift — and the legacy equal-weight pattern
        over the positive ranks is unchanged."""
        from chainermn_tpu.datasets import weighted_shard_counts

        assert weighted_shard_counts(10, [1.0, 0.0]) == [10, 0]
        # min_count lifts only the POSITIVE ranks
        assert weighted_shard_counts(10, [1.0, 1e-9, 0.0],
                                     min_count=1) == [9, 1, 0]
        # the remainder pattern over the data-owning ranks matches the
        # same split WITHOUT the probationary rank appended
        n = 1000
        with_probe = weighted_shard_counts(n, [1.0] * 64 + [0.0])
        assert with_probe[:64] == weighted_shard_counts(n, [1.0] * 64)
        assert with_probe[64] == 0

    def test_zero_weight_equalized_shard_pads_from_permutation_head(self):
        """The weight-0 shard's lockstep pad: under ``equalize`` it
        steps the same count per epoch as everyone (width = widest
        shard) but draws only re-served samples — the head of the
        epoch permutation — so full cover over the data-owning ranks
        is untouched."""
        from chainermn_tpu.datasets.scatter_dataset import scatter_index

        n, size = 103, 9  # 8 data ranks + 1 probe, ragged on purpose
        w = [1.0] * 8 + [0.0]
        widths, covered = set(), set()
        for r in range(size):
            order, s, e = scatter_index(n, size, r, weights=w,
                                        equalize=True)
            widths.add(e - s)
            if r < 8:
                covered.update(int(i) for i in order[s:e])
        assert len(widths) == 1  # lockstep width, probe included
        assert covered == set(range(n))  # data ranks still cover all
        # the probe shard re-serves exactly the permutation's head
        order, s, e = scatter_index(n, size, 8, weights=w,
                                    equalize=True)
        base, _s0, _e0 = scatter_index(n, size, 0, weights=w,
                                       equalize=True)
        np.testing.assert_array_equal(order[s:e], base[: e - s])

    def test_rescatter_preserves_base_permutation(self):
        from chainermn_tpu.datasets import rescatter, scatter_dataset

        class _Comm:
            process_count, process_index, rank, size = 4, 1, 1, 4

            def bcast_obj(self, x, root=0):
                return x

        data = list(range(40, 57))  # 17 samples, distinct values
        sub = scatter_dataset(data, _Comm(), shuffle=True, seed=7)
        w = [1.0, 0.5, 1.0, 1.0]
        sub2 = rescatter(sub, w)
        # same base permutation re-split: the union of unique indices
        # over all ranks is still the whole dataset, and this rank's
        # spec records the agreed weights
        assert sub2.scatter_spec["weights"] == tuple(w)
        np.testing.assert_array_equal(sub2.base_order, sub.base_order)
        # a plain SubDataset without scatter metadata is refused
        from chainermn_tpu.datasets import SubDataset

        bare = SubDataset(data, np.arange(17), 0, 5)
        with pytest.raises(ValueError, match="scatter_dataset"):
            rescatter(bare, w)


# ----------------------------------------------------------------------
class TestAdaptPolicy:
    """Tentpole (ISSUE 15): the hysteresis state machine, unit-pinned
    at fleet widths with no processes."""

    def _policy(self, **kw):
        from chainermn_tpu.resilience.adaptive import AdaptPolicy

        kw.setdefault("rebalance_after", 1)
        kw.setdefault("demote_after", 3)
        kw.setdefault("cooldown_windows", 1)
        return AdaptPolicy(**kw)

    def test_escalation_rebalance_cooldown_demote(self):
        p = self._policy()
        a1 = p.observe([3], world=16, iteration=1)
        assert a1[0]["action"] == "rebalance"
        assert a1[0]["weights"][3] == 0.5  # skewed away from the host
        # cooldown blocks the next window entirely
        assert p.observe([3], world=16, iteration=2) == []
        a3 = p.observe([3], world=16, iteration=3)
        assert a3 == [{"action": "demote", "process": 3, "streak": 3,
                       "iteration": 3}]

    def test_flap_suppression_decays_streak(self):
        # slow / recovered / slow / recovered ... never reaches the
        # demote threshold: a healthy window decays the streak
        p = self._policy(max_rebalances=0)
        for i, conv in enumerate(
            [[5], [], [5], [], [5], [], [5], []], start=1
        ):
            actions = p.observe(conv, world=16, iteration=i)
            assert actions == [], (i, actions)
            assert p.streaks.get(5, 0) <= 1

    def test_two_simultaneous_stragglers_one_weighted_map(self):
        p = self._policy()
        a = p.observe([3, 9], world=64, iteration=1)
        assert len(a) == 1 and a[0]["action"] == "rebalance"
        assert a[0]["processes"] == [3, 9]
        w = a[0]["weights"]
        assert len(w) == 64
        assert w[3] == w[9] == 0.5 and w[0] == 1.0

    def test_max_rebalances_caps_the_skew(self):
        p = self._policy(demote_after=99, cooldown_windows=0,
                         max_rebalances=2)
        kinds = [p.observe([7], world=16, iteration=i)
                 for i in range(1, 5)]
        assert [bool(k) for k in kinds] == [True, True, False, False]
        assert p.weights[7] == 0.25  # 0.5 ** 2, floored far above min

    def test_demote_picks_highest_streak_then_lowest_index(self):
        p = self._policy(rebalance_after=99, cooldown_windows=0)
        p.observe([2, 9], world=16, iteration=1)
        p.observe([2, 9], world=16, iteration=2)
        p.observe([9], world=16, iteration=3)
        a = p.observe([2, 9], world=16, iteration=4)
        # 9 has streak 4, 2 decayed to 2 (healthy window 3): 9 wins
        assert a[0] == {"action": "demote", "process": 9, "streak": 4,
                        "iteration": 4}

    def test_state_round_trips_and_resets_on_world_change(self):
        from chainermn_tpu.resilience.adaptive import AdaptPolicy

        p = self._policy()
        p.observe([3], world=16, iteration=1)
        p.observe([3], world=16, iteration=2)
        sd = p.state_dict()
        q = AdaptPolicy()
        q.load_state_dict(sd)
        assert q.streaks == {3: 2} and q.world == 16
        assert q.weights[3] == 0.5
        assert q.totals["rebalance"] == 1
        # same world: hysteresis continues where it left off
        q2 = AdaptPolicy(demote_after=3)
        q2.load_state_dict(sd)
        a = q2.observe([3], world=16, iteration=3)
        assert a[0]["action"] == "demote"
        # resized world: per-process maps reset (indices renamed),
        # run totals survive, the reset is observable
        q.observe([], world=15, iteration=9)
        assert q.streaks == {} and q.weights is None
        assert q.last_reset == (16, 15)
        assert q.totals["rebalance"] == 1

    def test_validation_is_eager(self):
        from chainermn_tpu.resilience.adaptive import AdaptPolicy

        with pytest.raises(ValueError, match="thresholds"):
            AdaptPolicy(rebalance_after=0)
        with pytest.raises(ValueError, match="rebalance_skew"):
            AdaptPolicy(rebalance_skew=1.0)
        with pytest.raises(ValueError, match="unknown actions"):
            AdaptPolicy(actions=("rebalance", "restart"))
        with pytest.raises(ValueError, match="probation_windows"):
            AdaptPolicy(probation_windows=0)
        with pytest.raises(ValueError, match="readmit_cooldown"):
            AdaptPolicy(readmit_cooldown_windows=-1)
        with pytest.raises(ValueError, match="promote_quorum"):
            AdaptPolicy(promote_quorum=0)

    def test_promote_decision_shape_and_readmit_cooldown(self):
        """Scale-up (ISSUE 16): ready hosts become one promote decision
        (world → world+k); a just-demoted host is held out until
        ``readmit_cooldown_windows`` report windows pass."""
        p = self._policy(readmit_cooldown_windows=2)
        hosts = [f"h{i}" for i in range(8)]
        a = p.observe([], world=8, iteration=4,
                      ready_hosts=["hx", "hy"], hosts=hosts)
        assert a == [{"action": "promote", "hosts": ["hx", "hy"],
                      "world": 8, "new_world": 10, "iteration": 4}]
        assert p.totals["promote"] == 1
        # demote h3 at window 2 — the NEXT two windows block its
        # re-admission, the third admits it
        p2 = self._policy(rebalance_after=99, demote_after=1,
                          cooldown_windows=0,
                          readmit_cooldown_windows=2)
        d = p2.observe([3], world=8, iteration=1, hosts=hosts)
        assert d[0]["action"] == "demote"
        assert p2.host_history["h3"] == {
            "streak": 1, "window": 1, "promoted": False,
        }
        assert p2.readmit_blocked("h3")
        assert p2.observe([], world=7, iteration=2,
                          ready_hosts=["h3"], hosts=hosts[:7]) == []
        a2 = p2.observe([], world=7, iteration=3,
                        ready_hosts=["h3"], hosts=hosts[:7])
        assert a2[0]["action"] == "promote"
        assert a2[0]["new_world"] == 8
        assert p2.host_history["h3"]["promoted"] is True

    def test_promote_quorum_holds_ready_hosts_for_one_restart(self):
        """``promote_quorum`` amortizes world re-formations: ready
        hosts are HELD (the watcher keeps them ready — nothing is
        consumed) until at least that many can join in one N→N+k
        restart; then they all promote together."""
        p = self._policy(promote_quorum=3)
        hosts = [f"h{i}" for i in range(6)]
        assert p.observe([], world=6, iteration=1,
                         ready_hosts=["hx"], hosts=hosts) == []
        assert p.observe([], world=6, iteration=2,
                         ready_hosts=["hx", "hy"], hosts=hosts) == []
        assert p.totals["promote"] == 0
        a = p.observe([], world=6, iteration=3,
                      ready_hosts=["hy", "hx", "hz"], hosts=hosts)
        assert a == [{"action": "promote",
                      "hosts": ["hx", "hy", "hz"],
                      "world": 6, "new_world": 9, "iteration": 3}]
        assert p.totals["promote"] == 1
        # a cooldown-blocked host does not count toward the quorum
        p2 = self._policy(rebalance_after=99, demote_after=1,
                          cooldown_windows=0, promote_quorum=2,
                          readmit_cooldown_windows=5)
        p2.observe([3], world=6, iteration=1, hosts=hosts)
        assert p2.observe([], world=5, iteration=2,
                          ready_hosts=["h3", "hx"],
                          hosts=hosts[:5]) == []

    def test_demote_wins_the_window_over_promote(self):
        p = self._policy(rebalance_after=99, demote_after=1,
                         cooldown_windows=0)
        a = p.observe([2], world=8, iteration=5, ready_hosts=["hx"],
                      hosts=[f"h{i}" for i in range(8)])
        assert [x["action"] for x in a] == ["demote"]
        # the ready host was NOT consumed: next (healthy) window
        # promotes it
        a2 = p.observe([], world=8, iteration=6, ready_hosts=["hx"],
                       hosts=[f"h{i}" for i in range(8)])
        assert [x["action"] for x in a2] == ["promote"]

    def test_flap_demote_probation_promote_convict_skips_to_demote(self):
        """Satellite (ISSUE 16): the full flap — demoted, re-admitted
        through probation, promoted, convicted again — skips the
        rebalance ladder: the effective streak starts from the
        pre-demotion history, so ONE fresh conviction trips
        ``demote_after`` again."""
        p = self._policy(demote_after=3, cooldown_windows=0,
                         readmit_cooldown_windows=0)
        hosts8 = [f"h{i}" for i in range(8)]
        # build h5's streak to demotion (cooldown off, rebalance fires
        # along the way — ignore the actions, watch the history)
        p.observe([5], world=8, iteration=1, hosts=hosts8)
        p.observe([5], world=8, iteration=2, hosts=hosts8)
        a = p.observe([5], world=8, iteration=3, hosts=hosts8)
        assert a[0] == {"action": "demote", "process": 5, "streak": 3,
                        "iteration": 3}
        assert p.host_history["h5"]["streak"] == 3
        # world shrank to 7 (per-process maps reset), h5 returns and
        # clears probation
        a = p.observe([], world=7, iteration=10, ready_hosts=["h5"],
                      hosts=hosts8[:7])
        assert a[0]["action"] == "promote"
        # grown world: h5 is now process 7; its FIRST re-conviction
        # goes straight to demote (3 history + 1 fresh >= 3), no
        # rebalance rung — and the fresh demotion re-records history
        hosts_new = hosts8[:7] + ["h5"]
        a = p.observe([7], world=8, iteration=20, hosts=hosts_new)
        assert a[0] == {"action": "demote", "process": 7, "streak": 4,
                        "iteration": 20}
        assert p.host_history["h5"]["promoted"] is False
        assert p.totals["demote"] == 2

    def test_readmitted_host_excluded_from_rebalance(self):
        p = self._policy(demote_after=99, cooldown_windows=0)
        hosts = [f"h{i}" for i in range(4)]
        p.host_history["h2"] = {"streak": 1, "window": 0,
                                "promoted": True}
        # h2 (process 2) convicts but is re-admitted: no rebalance for
        # it; a normal process still rebalances in the same window
        a = p.observe([1, 2], world=4, iteration=1, hosts=hosts)
        assert a[0]["action"] == "rebalance"
        assert a[0]["processes"] == [1]

    def test_host_history_round_trips_and_survives_resize(self):
        from chainermn_tpu.resilience.adaptive import AdaptPolicy

        p = self._policy(rebalance_after=99, demote_after=1,
                         cooldown_windows=0)
        p.observe([3], world=8, iteration=1,
                  hosts=[f"h{i}" for i in range(8)])
        sd = p.state_dict()
        q = AdaptPolicy()
        q.load_state_dict(sd)
        assert q.host_history == {
            "h3": {"streak": 1, "window": 1, "promoted": False},
        }
        assert q.totals["demote"] == 1
        # a resize resets per-process maps; host-keyed history survives
        q.observe([], world=7, iteration=2)
        assert q.streaks == {}
        assert q.host_history["h3"]["streak"] == 1


class TestCapacityWatcher:
    """Tentpole (ISSUE 16): the probation state machine over presence
    manifests — scan/evaluate with no processes."""

    def _watcher(self, tmp_path, **kw):
        from chainermn_tpu.resilience.adaptive import CapacityWatcher

        kw.setdefault("probation_windows", 2)
        return CapacityWatcher(str(tmp_path), **kw)

    def _publish(self, tmp_path, host, window, mean):
        from chainermn_tpu.resilience.adaptive import publish_presence

        return publish_presence(str(tmp_path), host, window=window,
                                step_mean_s=mean)

    def test_probation_clears_after_consecutive_clean_windows(
        self, tmp_path
    ):
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        w = self._watcher(tmp_path)
        means = {0: 0.10, 1: 0.10, 2: 0.11}
        slog = ResilienceLog()
        attach(slog)
        try:
            self._publish(tmp_path, "c9", 1, 0.10)
            assert w.evaluate(w.scan(), means) == []
            assert slog.counts.get("host_returned") == 1
            # the SAME manifest again: no new window, no progress
            assert w.evaluate(w.scan(), means) == []
            assert w.streaks["c9"] == 1
            self._publish(tmp_path, "c9", 2, 0.12)
            assert w.evaluate(w.scan(), means) == ["c9"]
            assert slog.counts.get("probation_pass") == 1
            # cleared hosts stay ready until promoted
            assert w.evaluate(w.scan(), means) == ["c9"]
        finally:
            detach(slog)

    def test_dirty_window_resets_the_streak(self, tmp_path):
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        w = self._watcher(tmp_path)
        means = {0: 0.10, 1: 0.10, 2: 0.10}
        slog = ResilienceLog()
        attach(slog)
        try:
            self._publish(tmp_path, "c9", 1, 0.10)
            w.evaluate(w.scan(), means)
            # window 2 is a straggler window (0.9 > 1.5 * 0.10)
            self._publish(tmp_path, "c9", 2, 0.9)
            assert w.evaluate(w.scan(), means) == []
            assert w.streaks["c9"] == 0
            holds = slog.events("probation_hold")
            assert holds[0].info["reason"] == "straggler"
            # two more clean windows needed from scratch
            self._publish(tmp_path, "c9", 3, 0.10)
            assert w.evaluate(w.scan(), means) == []
            self._publish(tmp_path, "c9", 4, 0.10)
            assert w.evaluate(w.scan(), means) == ["c9"]
        finally:
            detach(slog)

    def test_blocked_host_sighted_but_held(self, tmp_path):
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        w = self._watcher(tmp_path)
        means = {0: 0.10, 1: 0.10}
        self._publish(tmp_path, "c9", 1, 0.10)
        slog = ResilienceLog()
        attach(slog)
        try:
            assert w.evaluate(w.scan(), means, blocked=["c9"]) == []
            hold = slog.events("probation_hold")[0]
            assert hold.info["reason"] == "readmit_cooldown"
            assert "c9" in w.returned  # sighted all the same
            assert w.streaks.get("c9", 0) == 0
        finally:
            detach(slog)

    def test_no_measurement_holds_and_torn_manifest_skipped(
        self, tmp_path
    ):
        from chainermn_tpu.resilience.adaptive import presence_path

        w = self._watcher(tmp_path)
        # no world means yet (empty report): candidate cannot clear
        self._publish(tmp_path, "c9", 1, 0.10)
        assert w.evaluate(w.scan(), {}) == []
        assert w.streaks["c9"] == 0
        # a torn manifest (killed mid-write without the atomic rename)
        # is invisible to scan — never a crash
        os.makedirs(os.path.dirname(presence_path(str(tmp_path), "t")),
                    exist_ok=True)
        with open(presence_path(str(tmp_path), "t"), "w") as f:
            f.write('{"host": "t", "win')
        assert "t" not in w.scan()

    def test_publish_is_atomic_and_clearable(self, tmp_path):
        from chainermn_tpu.resilience.adaptive import (
            clear_presence, presence_path,
        )

        p = self._publish(tmp_path, "c3", 5, 0.2)
        assert p == presence_path(str(tmp_path), "c3")
        with open(p) as f:
            doc = json.load(f)
        assert doc == {"host": "c3", "window": 5, "step_mean_s": 0.2,
                       "state": "candidate"}
        # no tmp litter next to the manifest (atomic rename contract)
        assert os.listdir(os.path.dirname(p)) == ["host_c3.json"]
        clear_presence(str(tmp_path), "c3")
        assert not os.path.exists(p)
        clear_presence(str(tmp_path), "c3")  # idempotent

    def test_validation_is_eager(self, tmp_path):
        from chainermn_tpu.resilience.adaptive import CapacityWatcher

        with pytest.raises(ValueError, match="probation_windows"):
            CapacityWatcher(str(tmp_path), probation_windows=0)
        with pytest.raises(ValueError, match="straggler_factor"):
            CapacityWatcher(str(tmp_path), straggler_factor=1.0)


class _AgreeComm:
    """Mocked obj store for the decision agreement: optionally flaky
    (torn payload) for the first ``flaky`` exchanges, then returns the
    scripted peer payloads + this rank's own."""

    def __init__(self, n, flaky=0, peers=None):
        self.process_count = self.size = n
        self.process_index = 0
        self._flaky = flaky
        self._peers = peers
        self.exchanges = 0

    def bcast_obj(self, obj, root=0):
        return obj  # rank 0's view wins — this mock IS rank 0

    def allgather_obj(self, mine):
        from chainermn_tpu.resilience.errors import (
            PayloadCorruptionError,
        )

        self.exchanges += 1
        if self._flaky > 0:
            self._flaky -= 1
            raise PayloadCorruptionError(
                "decision payload failed to unpickle",
                site="obj_store.exchange",
            )
        peers = (self._peers if self._peers is not None
                 else [mine] * (self.process_count - 1))
        return [mine] + list(peers)


class TestAdaptiveAgreement:
    """Satellite (CI/lint): every policy exchange rides the existing
    lockstep retry — a torn payload during the rebalance agreement is
    retried on all ranks together, and a genuinely divergent decision
    raises on every rank before anyone acts."""

    def _ext(self, comm):
        from chainermn_tpu.resilience.adaptive import (
            AdaptiveExecution,
            AdaptPolicy,
        )

        return AdaptiveExecution(AdaptPolicy(), comm=comm)

    def test_torn_rebalance_agreement_retried_in_lockstep(self):
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        comm = _AgreeComm(16, flaky=1)
        ext = self._ext(comm)
        actions = [{"action": "rebalance", "processes": [3],
                    "weights": [1.0] * 16, "iteration": 4}]
        slog = ResilienceLog()
        attach(slog)
        try:
            ext._agree(4, actions)
        finally:
            detach(slog)
        assert comm.exchanges == 2  # torn once, re-exchanged
        assert slog.counts.get("retry") == 1
        assert slog.events("retry")[0].site == "adaptive.agree"

    def test_divergent_decision_raises_on_every_rank(self):
        from chainermn_tpu.resilience.errors import (
            AdaptDecisionMismatchError,
        )

        comm = _AgreeComm(4, peers=['{"other": "decision"}'] * 3)
        ext = self._ext(comm)
        with pytest.raises(AdaptDecisionMismatchError,
                           match="diverged at iteration 7"):
            ext._agree(7, [{"action": "demote", "process": 1}])

    def test_exhausted_retries_surface_the_transient_taxonomy(self):
        from chainermn_tpu.resilience.errors import TransientCommError

        comm = _AgreeComm(4, flaky=99)
        ext = self._ext(comm)
        with pytest.raises(TransientCommError):
            ext._agree(1, [{"action": "demote", "process": 1}])

    def test_torn_promote_agreement_retried_in_lockstep(self):
        """ISSUE 16 acceptance: the scale-up decision rides the SAME
        lockstep retry as rebalance/demote — a torn payload during the
        promote agreement re-exchanges on all ranks together."""
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        comm = _AgreeComm(8, flaky=1)
        ext = self._ext(comm)
        actions = [{"action": "promote", "hosts": ["c9"], "world": 8,
                    "new_world": 9, "iteration": 6}]
        slog = ResilienceLog()
        attach(slog)
        try:
            ext._agree(6, actions)
        finally:
            detach(slog)
        assert comm.exchanges == 2  # torn once, re-exchanged
        assert slog.counts.get("retry") == 1
        assert slog.events("retry")[0].site == "adaptive.agree"

    def test_divergent_promote_decision_raises_on_every_rank(self):
        """ISSUE 16 acceptance: a rank that decided a DIFFERENT grow
        (or none) raises AdaptDecisionMismatchError before anyone
        re-forms the world — mirroring the demote pin."""
        from chainermn_tpu.resilience.errors import (
            AdaptDecisionMismatchError,
        )

        other = json.dumps(
            {"iteration": 6, "actions": []}, sort_keys=True
        )
        comm = _AgreeComm(8, peers=[other] * 7)
        ext = self._ext(comm)
        with pytest.raises(AdaptDecisionMismatchError,
                           match="diverged at iteration 6"):
            ext._agree(6, [{"action": "promote", "hosts": ["c9"],
                            "world": 8, "new_world": 9,
                            "iteration": 6}])


class _StubReport:
    """Just enough MetricsReport surface for the extension."""

    def __init__(self, comm=None, means=None):
        self._comm = comm
        self.last_report = None
        self.straggler_processes = []
        self._means = dict(means or {})

    def window(self, iteration, stragglers):
        self.last_report = {"iteration": iteration, "rows": [],
                            "stragglers": list(stragglers)}
        self.straggler_processes = list(stragglers)

    def process_means(self, phase="step"):
        return dict(self._means)


class TestAdaptiveExecution:
    """The extension half of the tentpole: conviction stream in,
    applied rebalance / collective demotion out."""

    def _trainer(self, dataset):
        from chainermn_tpu.iterators import SerialIterator
        from chainermn_tpu.training.trainer import Trainer, Updater

        it = SerialIterator(dataset, 2, shuffle=False)
        return Trainer(Updater(it, lambda *a: None, None, None),
                       stop_trigger=(1, "iteration"))

    def _scattered(self, n_shards=4, rank=0, n=40):
        from chainermn_tpu.datasets import scatter_dataset

        class _Comm:
            process_count, process_index = n_shards, rank
            size = n_shards
            rank_ = rank

            def bcast_obj(self, x, root=0):
                return x

        return scatter_dataset(list(range(n)), _Comm(), shuffle=False,
                               seed=0)

    def test_rebalance_rescatters_live_iterator_and_remaps_cursor(self):
        from chainermn_tpu.resilience.adaptive import (
            AdaptiveExecution,
            AdaptPolicy,
        )
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        sub = self._scattered()  # 40 samples, 4 shards: width 10
        trainer = self._trainer(sub)
        saved = []

        class _Ckpt:
            def restore_trainer(self, t):
                return None

            def __call__(self, t):
                saved.append(t.iteration)

        trainer.extend(_Ckpt())
        for _ in range(3):  # advance the cursor to pos=6
            next(trainer.updater.iterator)
        rep = _StubReport(comm=_AgreeComm(4))
        ext = AdaptiveExecution(AdaptPolicy(), comm=_AgreeComm(4),
                                report=rep)
        trainer.extend(ext)
        ext.initialize(trainer)
        rep.window(iteration=5, stragglers=[2])
        slog = ResilienceLog()
        attach(slog)
        try:
            ext(trainer)
        finally:
            detach(slog)
        new_ds = trainer.updater.iterator.dataset
        assert new_ds is not sub
        assert new_ds.scatter_spec["weights"][2] == 0.5
        # width grew 10→12 (the skewed map pads every shard to the
        # widest) and the cursor remapped proportionally (6·12//10),
        # computed identically on every rank
        assert len(new_ds) == 12
        assert trainer.updater.iterator._pos == 7
        decisions = slog.events("adapt_decision")
        assert [e.info["action"] for e in decisions] == ["rebalance"]
        acts = slog.events("adapt_action")
        assert acts[0].info["applied"] is True
        assert slog.events("adaptive_iterator_remap")
        # the rebalance RE-COMMITTED the current step: the higher-
        # priority checkpointer saved before the shard map changed, so
        # without this re-save an auto-resume would restore the old
        # width's cursor against the new dataset (review regression)
        assert saved == [trainer.iteration]
        # the same window is never re-decided
        ext(trainer)
        assert len(slog.events("adapt_decision")) == 1

    def test_demotion_raises_collectively_with_peer_and_snapshot(self):
        from chainermn_tpu.resilience.adaptive import (
            AdaptiveExecution,
            AdaptPolicy,
        )
        from chainermn_tpu.resilience.errors import (
            DemotionRequiredError,
        )
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        trainer = self._trainer(list(range(8)))
        saved = []

        class _Ckpt:  # checkpointer double: record the forced save
            def restore_trainer(self, t):
                return None

            def __call__(self, t):
                saved.append(t.iteration)

        trainer.extend(_Ckpt())
        trainer.iteration = 9
        rep = _StubReport()
        ext = AdaptiveExecution(
            AdaptPolicy(demote_after=1, actions=("demote",)),
            comm=_AgreeComm(4), report=rep,
        )
        trainer.extend(ext)
        ext.initialize(trainer)
        rep.window(iteration=9, stragglers=[3])
        slog = ResilienceLog()
        attach(slog)
        try:
            with pytest.raises(DemotionRequiredError) as ei:
                ext(trainer)
        finally:
            detach(slog)
        assert ei.value.peer == 3
        assert ei.value.recoverable is False
        assert saved == [9]  # snapshot committed before the raise
        act = slog.events("adapt_action", "adaptive.demote")[0]
        assert act.info["checkpoint_step"] == 9

    def test_promote_commits_snapshot_and_raises_collectively(
        self, tmp_path
    ):
        """The scale-up half of the tentpole, unit shape: a candidate
        clears two probe windows, the agreed promote decision commits a
        snapshot at the decision iteration, emits the promote
        decision/action events, and raises PromotionRequiredError on
        the (mocked) world together."""
        from chainermn_tpu.resilience.adaptive import (
            AdaptiveExecution,
            AdaptPolicy,
            CapacityWatcher,
            publish_presence,
        )
        from chainermn_tpu.resilience.errors import (
            PromotionRequiredError,
        )
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        trainer = self._trainer(list(range(8)))
        saved = []

        class _Ckpt:
            def restore_trainer(self, t):
                return None

            def __call__(self, t):
                saved.append(t.iteration)

        trainer.extend(_Ckpt())
        rep = _StubReport(means={p: 0.1 for p in range(4)})
        ext = AdaptiveExecution(
            AdaptPolicy(), comm=_AgreeComm(4), report=rep,
            watcher=CapacityWatcher(str(tmp_path),
                                    probation_windows=2),
        )
        trainer.extend(ext)
        ext.initialize(trainer)
        assert ext._hosts == ["h0", "h1", "h2", "h3"]
        # probe window 1: sighted, streak 1, no decision yet
        publish_presence(str(tmp_path), "c9", window=1,
                         step_mean_s=0.11)
        trainer.iteration = 5
        rep.window(iteration=5, stragglers=[])
        slog = ResilienceLog()
        attach(slog)
        try:
            ext(trainer)
            assert slog.counts.get("host_returned") == 1
            assert not slog.events("adapt_decision")
            # probe window 2: clears probation -> agreed promote
            publish_presence(str(tmp_path), "c9", window=2,
                             step_mean_s=0.12)
            trainer.iteration = 6
            rep.window(iteration=6, stragglers=[])
            with pytest.raises(PromotionRequiredError) as ei:
                ext(trainer)
        finally:
            detach(slog)
        assert ei.value.hosts == ("c9",)
        assert ei.value.new_world == 5
        assert ei.value.recoverable is False
        assert saved == [6]  # snapshot committed before the raise
        dec = slog.events("adapt_decision")[0]
        assert dec.info["action"] == "promote"
        assert dec.info["host"] == "c9"
        assert dec.info["new_world"] == 5
        act = slog.events("adapt_action", "adaptive.promote")[0]
        assert act.info["checkpoint_step"] == 6
        assert act.info["hosts"] == "c9"
        assert ext.policy.totals["promote"] == 1

    def test_policy_state_rides_trainer_state_dict(self):
        import json as _json

        from chainermn_tpu.resilience.adaptive import (
            AdaptiveExecution,
            AdaptPolicy,
        )

        trainer = self._trainer(list(range(8)))
        rep = _StubReport()
        ext = AdaptiveExecution(AdaptPolicy(), comm=_AgreeComm(4),
                                report=rep)
        trainer.extend(ext)
        ext.initialize(trainer)
        ext.policy.observe([1], world=4, iteration=3)
        state = trainer.state_dict()
        assert _json.loads(state["adaptive"])["streaks"] == {"1": 1}
        # round-trip through a fresh trainer restores the hysteresis
        t2 = self._trainer(list(range(8)))
        ext2 = AdaptiveExecution(AdaptPolicy(), comm=_AgreeComm(4),
                                 report=_StubReport())
        t2.extend(ext2)
        t2.load_state_dict(state)
        assert ext2.policy.streaks == {1: 1}
        assert ext2.policy.weights[1] == 0.5

    def test_missing_report_fails_loudly_at_initialize(self):
        from chainermn_tpu.resilience.adaptive import AdaptiveExecution

        trainer = self._trainer(list(range(8)))
        ext = AdaptiveExecution()
        trainer.extend(ext)
        with pytest.raises(ValueError, match="MetricsReport"):
            ext.initialize(trainer)

    def test_run_adapt_attaches_the_extension_once(self):
        from chainermn_tpu.observability import MetricsReport
        from chainermn_tpu.resilience.adaptive import AdaptPolicy

        trainer = self._trainer(list(range(8)))
        trainer.stop_n, trainer.stop_unit = 0, "iteration"
        trainer.extend(MetricsReport(None, filename=None))
        with pytest.raises(TypeError, match="AdaptPolicy"):
            trainer.run(adapt=object())
        policy = AdaptPolicy(demote_after=7)
        trainer.run(adapt=policy)  # 0-iteration run: dispatch only
        ext = trainer._find_adaptive()
        assert ext is not None and ext.policy is policy
        n = len(trainer._extensions)
        trainer.run(adapt=policy)  # already attached: no duplicate
        assert len(trainer._extensions) == n

    def test_malformed_checkpointed_policy_state_degrades_gracefully(
        self,
    ):
        from chainermn_tpu.resilience.adaptive import (
            AdaptiveExecution,
            AdaptPolicy,
        )

        trainer = self._trainer(list(range(8)))
        ext = AdaptiveExecution(AdaptPolicy(), comm=_AgreeComm(4),
                                report=_StubReport())
        trainer.extend(ext)
        # a resharder-mangled leaf that is valid JSON but not an
        # object must warn and start fresh, never crash the restore
        with pytest.warns(UserWarning, match="hysteresis starts fresh"):
            trainer.load_state_dict(
                {"iteration": 3, "iterator": None, "adaptive": "[1, 2]"}
            )
        assert trainer.iteration == 3
        assert ext.policy.streaks == {}


class TestMetricsWarmupWindow:
    """Satellite (ISSUE 15): the post-resume warmup-window skip — the
    compile-dominated first window after a reshard is excluded from
    conviction BY CONTRACT (``warmup_windows=1``), not by leaning on
    the materiality floor."""

    def _trainer(self, resumed):
        from chainermn_tpu.resilience.log import ResilienceLog

        class _T:
            iteration = 4
            observation = {}
            resilience_log = ResilienceLog()

        t = _T()
        if resumed:
            t.resilience_log.record(
                "elastic_restart", "trainer.run_elastic",
                restored_step=3, world=15,
            )
        return t

    def _report(self, trainer, n=16, straggler=5, **kw):
        """A report over a scripted N-process world, with a live
        telemetry installed for its lifetime (uninstalled by its own
        finalize)."""
        from chainermn_tpu.observability import MetricsReport

        rep = MetricsReport(_ScriptedSummaryComm(n, straggler),
                            filename=None, **kw)
        rep.initialize(trainer)
        assert rep._own_telemetry is not None  # it owns the install
        return rep

    def test_first_post_resume_window_skipped_second_convicts(self):
        from chainermn_tpu.resilience.log import (
            ResilienceLog, attach, detach,
        )

        trainer = self._trainer(resumed=True)
        rep = self._report(trainer)
        slog = ResilienceLog()
        attach(slog)
        try:
            rep(trainer)
            # the scripted world WOULD convict (the straggler's phase
            # is far past factor and floor) — the warmup contract
            # skips it anyway
            assert rep.straggler_processes == []
            assert slog.counts.get("straggler_warmup_skip") == 1
            assert not slog.events("straggler")
            trainer.iteration = 5
            rep(trainer)
            assert rep.straggler_processes == [5]
            assert slog.events("straggler")
        finally:
            detach(slog)
            rep.finalize()

    def test_fresh_run_skips_nothing(self):
        trainer = self._trainer(resumed=False)
        rep = self._report(trainer)
        try:
            rep(trainer)
            assert rep.straggler_processes == [5]
        finally:
            rep.finalize()

    def test_midrun_auto_resume_rearms_the_skip(self):
        trainer = self._trainer(resumed=False)
        rep = self._report(trainer)
        try:
            rep(trainer)
            assert rep.straggler_processes == [5]
            # an auto-resume lands on the log mid-run: the next window
            # skips, the one after convicts again
            trainer.resilience_log.record(
                "restart", "obj_store.exchange",
                restored_step=2, restarts=1,
            )
            trainer.iteration = 5
            rep(trainer)
            assert rep.straggler_processes == []
            trainer.iteration = 6
            rep(trainer)
            assert rep.straggler_processes == [5]
        finally:
            rep.finalize()

    def test_warmup_zero_opts_out(self):
        trainer = self._trainer(resumed=True)
        rep = self._report(trainer, warmup_windows=0)
        try:
            rep(trainer)
            assert rep.straggler_processes == [5]
        finally:
            rep.finalize()


class _ScriptedSummaryComm:
    """An obj store whose allgather returns a full scripted world of
    phase summaries (this rank's live payload replaced by script):
    drives MetricsReport.__call__ through conviction without
    processes."""

    def __init__(self, n, straggler):
        self.process_count = self.size = n
        self.process_index = 0
        self._n, self._straggler = n, straggler

    def allgather_obj(self, local):
        return list(_phase_data(self._n, {self._straggler}).values())


class TestChainReshardBitIdentity:
    """Satellite/tentpole contract: the 16→12→14→8 ZeRO block-reshard
    CHAIN is bit-identical to a fresh partition of the global state at
    every leg — composition introduces no drift."""

    @staticmethod
    def _fresh(flat, world):
        k = -(-flat.size // world)  # ceil
        out = np.zeros(world * k, flat.dtype)
        out[: flat.size] = flat
        return out.reshape(world, k)

    def test_chain_16_12_14_8_bit_identical_at_every_leg(self):
        from chainermn_tpu.resilience.elastic import reshard_blocked_leaf

        rng = np.random.RandomState(0)
        flat = rng.randn(1003).astype(np.float32)  # indivisible on purpose
        state = self._fresh(flat, 16)
        for world in (12, 14, 8):
            want = self._fresh(flat, world)
            state = reshard_blocked_leaf(state, want.shape)
            np.testing.assert_array_equal(state, want)

    def test_momentum_oracle_matches_closed_form_sgd(self):
        # mom=0 collapses to plain sgd's closed form — the oracle's own
        # sanity pin
        traj = momentum_oracle(5, lr=0.1, mom=0.0, c=0.5, dim=3)
        for k, w in enumerate(traj, start=1):
            np.testing.assert_allclose(
                w, 0.5 * (1 - 0.9 ** k) * np.ones(3), rtol=1e-12
            )


# ----------------------------------------------------------------------
class TestFleetReportMerge:
    def _write_events(self, path, rows):
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    def _ev(self, kind, t, process=0, site="s", **info):
        return {"kind": kind, "site": site, "process": process,
                "time": t, "monotonic": t, "info": info}

    def test_merge_orders_across_legs_and_processes(self, tmp_path):
        self._write_events(tmp_path / "leg0_p1_events.jsonl", [
            self._ev("fault_injected", 10.0, process=1, fault="die"),
        ])
        self._write_events(tmp_path / "leg1_p0_events.jsonl", [
            self._ev("world_reformed", 20.0),
            self._ev("elastic_reshard", 21.0),
        ])
        self._write_events(tmp_path / "leg1_p0_trainer_events.jsonl", [
            self._ev("elastic_reshard", 21.0),  # duplicate: deduped
            self._ev("elastic_restart", 22.0),
        ])
        rep = FleetReport.from_scratch(tmp_path)
        assert rep.counts == {
            "fault_injected": 1, "world_reformed": 1,
            "elastic_reshard": 1, "elastic_restart": 1,
        }
        rep.assert_order("fault_injected", "world_reformed",
                         "elastic_reshard", "elastic_restart")
        assert rep.processes == {"leg0": [1], "leg1": [0]}

    def test_order_violation_raises_with_post_mortem(self, tmp_path):
        self._write_events(tmp_path / "leg0_p0_events.jsonl", [
            self._ev("world_reformed", 5.0),
            self._ev("fault_injected", 9.0),
        ])
        rep = FleetReport.from_scratch(tmp_path)
        with pytest.raises(AssertionError, match="does not precede"):
            rep.assert_order("fault_injected", "world_reformed")
        with pytest.raises(AssertionError, match="no 'retry' event"):
            rep.assert_order("retry")

    def test_trace_spans_anchor_on_wall0_and_torn_tail_skipped(
        self, tmp_path
    ):
        with open(tmp_path / "leg0_p0_trace.jsonl", "w") as f:
            f.write(json.dumps({
                "type": "meta", "name": "timeline.meta", "t": 0.0,
                "process": 0, "tid": 0, "args": {"wall0": 100.0},
            }) + "\n")
            f.write(json.dumps({
                "type": "span", "name": "step", "t": 2.5, "dur": 0.1,
                "process": 0, "tid": 0, "args": {},
            }) + "\n")
            f.write('{"type": "span", "name": "torn')  # killed mid-write
        self._write_events(tmp_path / "leg0_p0_events.jsonl", [
            self._ev("fault_injected", 101.0),
        ])
        rep = FleetReport.from_scratch(tmp_path)
        spans = rep.events("span:step")
        assert len(spans) == 1 and spans[0]["wall"] == 102.5
        # the span slots in between on the shared wall clock
        rep.assert_order("fault_injected", "span:step")

    def test_timeline_meta_row_export(self, tmp_path):
        from chainermn_tpu.observability.timeline import Timeline

        tl = Timeline(label="x")
        with tl.span("work"):
            pass
        path = tl.to_jsonl(str(tmp_path / "t.jsonl"), meta=True)
        rows = [json.loads(l) for l in open(path)]
        assert rows[0]["type"] == "meta"
        assert rows[0]["args"]["wall0"] == tl.wall0
        assert [r["name"] for r in rows[1:]] == ["work"]
        # default export unchanged: no meta row
        path2 = tl.to_jsonl(str(tmp_path / "t2.jsonl"))
        rows2 = [json.loads(l) for l in open(path2)]
        assert all(r["type"] != "meta" for r in rows2)


class TestStreamingSink:
    def test_events_flushed_per_emit(self, tmp_path):
        from chainermn_tpu.resilience.log import (
            JsonlFileSink, attach, detach, emit,
        )

        sink = JsonlFileSink(str(tmp_path / "ev.jsonl"))
        attach(sink)
        try:
            emit("fault_injected", "site.a", fault="die", call=3)
            # on disk BEFORE any close/flush call — the os._exit case
            rows = [json.loads(l) for l in open(tmp_path / "ev.jsonl")]
        finally:
            detach(sink)
            sink.close()
        assert len(rows) == 1
        assert rows[0]["kind"] == "fault_injected"
        assert rows[0]["info"] == {"fault": "die", "call": 3}
        assert "monotonic" in rows[0] and "time" in rows[0]
        # the sink is still a queryable ResilienceLog
        assert sink.counts == {"fault_injected": 1}


# ----------------------------------------------------------------------
# process-spawning tier-1 pieces: the budget teardown and the 8-proc
# smoke of the full machinery (the 16+-rank worlds are `slow`)
# ----------------------------------------------------------------------
# hard wall-clock budget for the tier-1 smoke, documented in
# tests/README.md — the budget is a deadlock detector on a timeshared
# host, not a perf assertion
SMOKE_BUDGET_S = 240


@pytest.mark.multiprocess
class TestFleetWorldBudget:
    def test_overrun_tears_down_loudly(self, tmp_path):
        # the sleep scenario wedges unconditionally, so ANY budget
        # catches it — a small one keeps this tier-1 test cheap
        w = FleetWorld(1, tmp_path, budget_s=5, label="wedge")
        with pytest.raises(FleetBudgetError) as ei:
            w.launch("sleep", {"sleep_s": 3600})
        msg = str(ei.value)
        assert "exceeded its 5s wall-clock budget" in msg
        assert "process 0" in msg  # the tail is quoted


@pytest.mark.multiprocess
class TestFleetSmoke8:
    def test_wave_plus_reshard_8_to_6_on_oracle(self, tmp_path):
        """The tier-1 smoke of the full fleet machinery (ISSUE 14
        acceptance, 8-process shape): a torn rendezvous payload
        (lockstep-retried), a preemption wave killing processes 6 and 7
        at step 3, and one elasticity-chain leg resuming at world 6
        through the checkpoint resharder onto the single-world numpy
        oracle — with the merged FleetReport asserting the
        fault→retry→reform→reshard→resume event order.

        Also the regression test for the wide-world defect this
        scenario surfaced at 16 processes (and 2-process worlds never
        lost): the coordination service's peer-death propagation
        hard-aborts the wave's SURVIVORS, racing their epilogue.  The
        fix is epilogue-before-wave (worker.scenario_chain_leg) +
        REAPED acceptance (world.assert_ok) — every survivor's RESULT
        payload and streamed artifacts must exist despite any reap,
        and the resume leg must still find all of leg0's snapshots."""
        chain = ElasticityChain(str(tmp_path), [
            ChainLeg(n_procs=8, n_steps=3, wave_at=3,
                     wave_processes=(6, 7), torn_calls=(1,)),
            ChainLeg(n_procs=6, n_steps=5),
        ], budget_s=SMOKE_BUDGET_S)
        out = chain.run()
        legs = out["legs"]
        # every leg-0 process published its payload BEFORE the wave —
        # victims included (their RESULT precedes their die)
        assert sorted(legs[0]) == list(range(8))
        assert all(p["steps_saved"] == 2 for p in legs[0].values())
        assert sorted(legs[1]) == [0, 1, 2, 3, 4, 5]
        for p in legs[1].values():
            assert p["oracle_match"] is True
            assert p["resumed_step"] == 2
            assert p["resized"] == [8, 6]
            assert p["iteration"] == 5
        rep = out["report"]
        rep.assert_order("fault_injected", "retry", "world_reformed",
                         "elastic_reshard", "elastic_restart")
        # the wave's victims left their die records via the streaming
        # sink despite os._exit
        dies = [e for e in rep.events("fault_injected")
                if e["info"].get("fault") == "die"]
        assert sorted(e["process"] for e in dies) == [6, 7]
        assert all(e["leg"] == "leg0" for e in dies)
        # every leg-1 process re-agreed and resumed
        restarts = rep.events("elastic_restart")
        assert sorted(e["process"] for e in restarts) == [0, 1, 2, 3, 4, 5]


@pytest.mark.multiprocess
class TestAdaptiveSmoke8:
    def test_migrating_straggler_rebalance_then_demote_8_to_7(
        self, tmp_path
    ):
        """The self-healing-runtime tier-1 smoke (ISSUE 15 acceptance,
        8-process shape): a straggler migrates 3→5 across report
        windows; the policy REBALANCES each conviction (weighted
        re-scatter agreed through the lockstep exchange, live iterator
        cursor remapped) and, when rank 5's streak outlives the
        hysteresis window, DEMOTES it — a snapshot committed at the
        decision iteration, ``DemotionRequiredError`` on every rank
        together.  The 7-process resume leg reshards onto the numpy
        sgd+momentum oracle from exactly that step, and the merged
        report asserts detect→decide→act→recover end to end."""
        sched = (FaultSchedule()
                 .straggler(3, window=(1, 2), delay=0.6)
                 .straggler(5, window=(3, 12), delay=0.6))
        world = FleetWorld(8, str(tmp_path), schedule=sched,
                           budget_s=SMOKE_BUDGET_S, label="leg0")
        from chainermn_tpu.fleet import REAPED

        res = world.launch(
            "adaptive_leg",
            {"n_steps": 12, "demote_after": 3, "linger_s": 1.5},
            expect_exit={p: REAPED for p in range(8)},
        )
        p1 = res.payloads()
        assert sorted(p1) == list(range(8))
        d = p1[0]["iteration"]
        for p in p1.values():
            assert p["demoted"] == 5  # the MIGRATED-to rank, never 3
            assert p["iteration"] == d
            assert p["oracle_match"] is True
            assert p["n_rebalances"] >= 1
            assert p["rebalance_applied"] is True
            assert 3 in p["stragglers"] and 5 in p["stragglers"]
        # resume leg: 8→7 through the checkpoint resharder, from
        # exactly the demotion's snapshot — no step lost
        res2 = FleetWorld(7, str(tmp_path), budget_s=SMOKE_BUDGET_S,
                          label="leg1").launch(
            "chain_leg",
            {"n_steps": d + 3, "wave_at": None, "lr": 0.1, "mom": 0.9,
             "dim": 4, "straggler": False, "report_every": 1},
            expect_exit={},
        )
        for p in res2.payloads().values():
            assert p["resumed_step"] == d
            assert p["resized"] == [8, 7]
            assert p["oracle_match"] is True
            assert p["iteration"] == d + 3
        rep = FleetReport.from_scratch(str(tmp_path))
        rep.assert_order(
            "fault_injected", "straggler", "adapt_decision",
            "world_reformed", "elastic_reshard", "elastic_restart",
        )
        decisions = rep.events("adapt_decision")
        reb = [e for e in decisions
               if e["info"]["action"] == "rebalance"]
        dem = [e for e in decisions if e["info"]["action"] == "demote"]
        assert reb and dem
        # escalation order: data was rebalanced before anyone was shed
        assert min(e["wall"] for e in reb) < min(
            e["wall"] for e in dem
        )
        assert {e["info"]["process"] for e in dem} == {5}
        # the committed demote snapshot is the step the world resumed
        acts = [e for e in rep.events("adapt_action")
                if e["info"]["action"] == "demote"]
        assert {e["info"]["checkpoint_step"] for e in acts} == {d}


@pytest.mark.multiprocess
class TestGrowSmoke8:
    def test_probation_promote_7_to_8_on_oracle(self, tmp_path):
        """The scale-UP tier-1 smoke (ISSUE 16 acceptance, 8-process
        shape): a 7-process training world runs with the capacity
        watcher while a CONCURRENT 1-process probe world publishes
        presence manifests for host h7 into the shared scratch.  The
        watcher holds h7 under probation for 2 clean windows, the
        agreed decision commits a snapshot and raises
        ``PromotionRequiredError`` on every rank together, rank 0 posts
        h7's admission marker, and the 8-process resume leg reshards
        onto the numpy sgd+momentum oracle from exactly the decision
        step — the candidate's first participation in the world.  The
        merged report pins the full promote chain: host_returned →
        probation_pass → adapt_decision → adapt_action →
        world_reformed → elastic_reshard → elastic_restart."""
        from chainermn_tpu.fleet import REAPED

        # a world-wide pace floor: probe/world step-mean RATIOS stay
        # noise-robust on a timeshared host (the probe is never slower
        # than 1.5x the world's 0.2s median)
        pace = FaultSchedule().pace(window=(1, 300), delay=0.2)
        grow = FleetWorld(7, str(tmp_path), schedule=pace,
                          budget_s=SMOKE_BUDGET_S, label="leg0").start(
            "grow_leg",
            {"n_steps": 300, "probation_windows": 2,
             "promote_quorum": 1, "report_every": 1, "linger_s": 1.5},
        )
        probe = FleetWorld(1, str(tmp_path), budget_s=SMOKE_BUDGET_S,
                           label="probe0").start(
            "probe_host",
            {"host": "h7", "world": 7, "steps_per_window": 3,
             "window_sleep_s": 0.25, "max_windows": 400},
        )
        # the promotion exits every rank together — REAPED, like the
        # demote leg
        res = grow.wait(expect_exit={p: REAPED for p in range(7)})
        pg = res.payloads()
        assert sorted(pg) == list(range(7))
        d = pg[0]["iteration"]
        for p in pg.values():
            assert p["promote"] == {"hosts": ["h7"], "new_world": 8}
            assert p["iteration"] == d
            assert p["oracle_match"] is True
            assert p["resumed_step"] is None  # fresh leg, not a resume
        pp = probe.wait(expect_exit={}).payloads()[0]
        assert pp["promoted"] is True
        assert pp["admission"]["new_world"] == 8
        assert pp["admission"]["checkpoint_step"] == d
        assert pp["windows"] >= 2  # probation took real probe windows
        # resume leg: 7→8 through the checkpoint resharder from exactly
        # the decision snapshot — no step lost across the growth
        res2 = FleetWorld(8, str(tmp_path), budget_s=SMOKE_BUDGET_S,
                          label="leg1").launch(
            "chain_leg",
            {"n_steps": d + 3, "wave_at": None, "lr": 0.1, "mom": 0.9,
             "dim": 4, "straggler": False, "report_every": 1},
            expect_exit={},
        )
        for p in res2.payloads().values():
            assert p["resumed_step"] == d
            assert p["resized"] == [7, 8]
            assert p["oracle_match"] is True
            assert p["iteration"] == d + 3
        rep = FleetReport.from_scratch(str(tmp_path))
        rep.assert_order(
            "host_returned", "probation_pass", "adapt_decision",
            "adapt_action", "world_reformed", "elastic_reshard",
            "elastic_restart",
        )
        promos = [e for e in rep.events("adapt_decision")
                  if e["info"].get("action") == "promote"]
        assert promos
        assert {e["info"]["host"] for e in promos} == {"h7"}
        assert {e["info"]["new_world"] for e in promos} == {8}
        # the committed promote snapshot is the step the world resumed
        acts = [e for e in rep.events("adapt_action")
                if e["info"].get("action") == "promote"]
        assert {e["info"]["checkpoint_step"] for e in acts} == {d}

"""The perf doc's measured table must be a function of the bench JSON.

Round 2's doc hand-copied numbers and contradicted the driver-captured
bench (0.92x vs 1.043x double-buffering).  docs/performance.md now
embeds a generated table between markers declaring its source file;
this test regenerates from that source and fails on any drift — a
stale or hand-edited number cannot be committed silently.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_measured_table_matches_declared_source():
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "gen_perf_table.py")],
        capture_output=True, text=True,
    )
    assert r.returncode == 0, (
        f"doc drifted from its bench source:\n{r.stdout}{r.stderr}"
    )
    assert "matches" in r.stdout


def test_generator_output_shape():
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    try:
        from gen_perf_table import generate
    finally:
        sys.path.pop(0)

    table = generate(os.path.join(REPO, "BENCH_r02.json"))
    lines = table.splitlines()
    assert lines[0].startswith("| config |")
    # headline + every config row present
    assert any("resnet50 (headline)" in l for l in lines)
    assert any("seq2seq_mp" in l for l in lines)
    assert any("moe_lm" in l for l in lines)

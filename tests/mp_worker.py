"""Multi-process test worker — runs one scenario inside a real
``jax.distributed`` process.

Parity: the reference's distributed tests are real multi-process runs
(``mpiexec -n 2 pytest``, SURVEY.md section 4 "real small world, no
mocks").  The TPU rebuild's analogue: ``test_multiprocess.py`` spawns N
copies of this script, each initializing ``jax.distributed`` against a
shared local coordinator, with CPU devices standing in for per-host TPU
chips.  Every multi-host-only code path (KV-store object transport,
``broadcast_one_to_all``, ``make_array_from_process_local_data``,
checkpoint agreement, barrier, the global except hook) executes for real.

Invocation (by test_multiprocess.py, not by hand):
    python mp_worker.py <scenario> <coordinator_port> <process_id> \
        <num_processes> <scratch_dir>

Prints ``RESULT <json>`` on success; exit code 0.  Scenarios that are
*supposed* to die (except hook) exit non-zero by design.
"""

import json
import os
import sys
import time


def main():
    scenario, port, pid, nproc, scratch = (
        sys.argv[1],
        sys.argv[2],
        int(sys.argv[3]),
        int(sys.argv[4]),
        sys.argv[5],
    )

    # process-targeted fault specs (FaultSpec(process=...)) resolve the
    # index from this env var — set before any injector can fire
    os.environ.setdefault("CHAINERMN_TPU_FAULT_PROCESS_INDEX", str(pid))

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        # Older jax (<= 0.4.x) does not enable cross-process CPU
        # collectives unless the gloo implementation is selected; newer
        # releases default to it (and may drop the option — hence the
        # guard).  Without this, every multihost_utils collective dies
        # with "Multiprocess computations aren't implemented on the CPU
        # backend".
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=nproc, process_id=pid
    )

    out = globals()[f"scenario_{scenario}"](pid, nproc, scratch)
    print("RESULT " + json.dumps(out or {}), flush=True)


def _comm(name="tpu", **kw):
    import chainermn_tpu as cmn

    return cmn.create_communicator(name, **kw)


# ----------------------------------------------------------------------
def scenario_obj_transport(pid, nproc, scratch):
    """MultiprocessObjStore: send/recv (KV store), bcast/gather/allgather
    (host collectives), chunk protocol, tuple + array payloads."""
    import numpy as np

    comm = _comm()
    assert comm.process_count == nproc

    # ring send/recv of a composite payload (tuple with an array, as in
    # the reference's _MessageType protocol tests)
    payload = ({"pid": pid}, np.arange(pid + 3, dtype=np.float32))
    comm.send_obj(payload, dest=(pid + 1) % nproc, tag=5)
    got = comm.recv_obj(source=(pid - 1) % nproc, tag=5)
    src = (pid - 1) % nproc
    assert got[0] == {"pid": src}, got
    np.testing.assert_array_equal(got[1], np.arange(src + 3, dtype=np.float32))

    # two queued messages to the same (dest, tag) arrive FIFO
    comm.send_obj("first", dest=(pid + 1) % nproc, tag=6)
    comm.send_obj("second", dest=(pid + 1) % nproc, tag=6)
    assert comm.recv_obj(source=src, tag=6) == "first"
    assert comm.recv_obj(source=src, tag=6) == "second"

    # collectives
    assert comm.bcast_obj(f"from-{pid}") == "from-0"
    assert comm.allgather_obj(pid * 11) == [i * 11 for i in range(nproc)]
    assert comm.gather_obj(pid + 1) == list(range(1, nproc + 1))
    assert comm.allreduce_obj(pid + 1) == sum(range(1, nproc + 1))

    # a payload above one chunk would need >256MB; instead verify a
    # multi-MB array round-trips intact through the KV store
    big = np.random.RandomState(pid).bytes(2_000_000)
    comm.send_obj(big, dest=(pid + 1) % nproc, tag=7)
    got = comm.recv_obj(source=src, tag=7)
    assert got == np.random.RandomState(src).bytes(2_000_000)
    return {"size": comm.size}


def scenario_bcast_data(pid, nproc, scratch):
    """bcast_data must make every process agree bit-for-bit with process
    0's parameters (parity: initial-weight sync of bcast_data(model))."""
    import numpy as np

    comm = _comm()
    tree = {
        "w": np.full((4, 4), float(pid + 1), np.float32),
        "b": np.arange(4, dtype=np.float32) + 100 * pid,
        "nested": [np.float32(pid), np.ones((2,), np.float32) * pid],
    }
    out = comm.bcast_data(tree)
    want = {
        "w": np.full((4, 4), 1.0, np.float32),
        "b": np.arange(4, dtype=np.float32),
        "nested": [np.float32(0.0), np.zeros((2,), np.float32)],
    }
    np.testing.assert_array_equal(np.asarray(out["w"]), want["w"])
    np.testing.assert_array_equal(np.asarray(out["b"]), want["b"])
    np.testing.assert_array_equal(
        np.asarray(out["nested"][1]), want["nested"][1]
    )
    # replicated across every device of the mesh
    assert len(out["w"].sharding.device_set) == comm.size
    return {}


def scenario_train_step(pid, nproc, scratch):
    """build_train_step with per-process local batches: the multi-process
    ``_place_batch`` path (make_array_from_process_local_data) + psum
    gradient sync must reproduce the single-controller oracle."""
    import numpy as np
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.optimizers import build_train_step

    comm = _comm()
    n_local = comm.size // comm.process_count

    def loss_fn(params, batch):
        x = batch
        return 0.5 * jnp.sum((params["w"] - x.mean(axis=0)) ** 2)

    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
    params = {"w": jnp.zeros((4,))}
    step = build_train_step(comm, loss_fn, opt, donate=False)
    params, opt_state = step.place(params, opt.init(params))

    # global batch row r = all-r; this process holds rows
    # [pid*n_local, (pid+1)*n_local)
    local_rows = np.stack(
        [
            np.full((4,), float(pid * n_local + i), np.float32)
            for i in range(n_local)
        ]
    )
    w = np.zeros((4,), np.float64)
    for _ in range(3):
        params, opt_state, metrics = step(params, opt_state, local_rows)
        # oracle: grad = mean_r(w - r)
        w = w - 0.1 * (w - np.mean(np.arange(comm.size)))
    got = np.asarray(params["w"])
    np.testing.assert_allclose(got, w, rtol=1e-5)
    return {"final_w": float(got[0]), "loss": float(metrics["loss"])}


def scenario_checkpoint(pid, nproc, scratch):
    """Checkpoint save / newest-common-step agreement / resume across
    real processes (parity: the allgather-inventories protocol)."""
    import numpy as np
    import jax.numpy as jnp
    import chainermn_tpu as cmn

    comm = _comm()

    # Part 1: shared-FS orbax checkpoint of *global* (mesh-replicated)
    # arrays — collective save, agreement, resume, bit-equal restore.
    ckpt = cmn.create_multi_node_checkpointer(
        "mp", comm, path=os.path.join(scratch, "ckpt")
    )
    state3 = {
        "params": comm.bcast_data({"w": jnp.arange(8.0)}),
        "meta": {"it": 3},
    }
    ckpt.save(3, state3)
    state7 = {
        "params": comm.bcast_data({"w": jnp.arange(8.0) + 7}),
        "meta": {"it": 7},
    }
    ckpt.save(7, state7)
    assert ckpt.newest_common_step() == 7

    step, restored = ckpt.resume(like=state7)
    assert step == 7, step
    np.testing.assert_allclose(
        np.asarray(restored["params"]["w"]), np.arange(8.0) + 7
    )
    assert int(np.asarray(restored["meta"]["it"])) == 7

    # Part 2: the agreement protocol itself with genuinely divergent
    # inventories — per-process directories mimic the reference's
    # per-rank local disk: process 0 has {1,2,5}, others {1,5,8};
    # the newest COMMON step is 5.
    local = cmn.create_multi_node_checkpointer(
        "loc", comm, path=os.path.join(scratch, f"local_{pid}")
    )
    mine = [1, 2, 5] if pid == 0 else [1, 5, 8]
    for s in mine:
        os.makedirs(local._step_dir(s), exist_ok=True)
    assert sorted(local._available_steps()) == mine
    assert local.newest_common_step() == 5
    return {"resumed_step": step}


def scenario_composed_mesh(pid, nproc, scratch):
    """The composed DP x SP x TP x EP step across real processes: a
    (2, 2, 2) mesh spanning two jax.distributed processes (4 CPU chips
    each), MoeTransformerLM with ring attention / Megatron TP / expert
    all_to_all / vocab-parallel embedding+head, per-process local batch
    rows.  Asserts the loss is finite, identical on every process, and
    decreasing."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import chainermn_tpu as cmn
    from chainermn_tpu.models.moe_transformer import (
        MoeTransformerLM,
        moe_lm_loss,
        moe_param_specs,
    )
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.parallel import sharded_init

    comm = _comm("mesh", sp_size=2, tp_size=2)
    assert comm.process_count == nproc and comm.size == 8

    B, S, V = 4, 16, 64
    model = MoeTransformerLM(
        vocab_size=V, d_model=32, n_heads=4, n_layers=2, n_experts=4,
        d_ff=64, moe_every=2, k=2, capacity=B * S * 2, max_len=S,
        dtype=jnp.float32, seq_axis="mn_seq", tp_axis="mn_model",
        expert_axis="mn_model", vocab_parallel=True,
        aux_stat_axes=("mn_data", "mn_seq", "mn_model"),
    )
    toks_global = np.random.RandomState(0).randint(0, V, (B, S))
    sample = jnp.asarray(toks_global)  # replicated sample for init shape
    params, specs = sharded_init(
        lambda t: model.init(jax.random.PRNGKey(0), t),
        comm.mesh, (P("mn_data", "mn_seq"),), moe_param_specs, sample,
    )
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)

    def loss_fn(p, b):
        return moe_lm_loss(
            model.apply(p, b), b, seq_axis="mn_seq",
            model_axis="mn_model", aux_coef=1e-2, vocab_parallel=True,
        )

    step = build_train_step(
        comm, loss_fn, opt, data_axes=comm.data_axis_names,
        param_specs=specs, batch_specs=P("mn_data", "mn_seq"),
        donate=False,
    )
    params, opt_state = step.place(params, opt.init(params))

    # per-process rows: the data axis spans processes, so each process
    # feeds its own slice of the global batch
    rows_per_proc = B // nproc
    local = toks_global[pid * rows_per_proc: (pid + 1) * rows_per_proc]
    losses = []
    for _ in range(3):
        params, opt_state, m = step(params, opt_state, local)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    # every process must see the identical (psum'd) loss sequence
    all_losses = comm.allgather_obj(losses)
    for other in all_losses[1:]:
        np.testing.assert_allclose(other, all_losses[0], rtol=1e-6)
    return {"losses": losses}


def scenario_iterators(pid, nproc, scratch):
    """Multi-process data layer (reference: _multi_node_iterator /
    _synchronized_iterator under mpiexec): the per-batch ``bcast_obj``
    loop of create_multi_node_iterator and the seed agreement of
    create_synchronized_iterator across real processes — including a
    non-zero ``rank_master`` owned by the LAST process, pinning the
    root-aware bcast_obj contract."""
    import numpy as np
    from chainermn_tpu.iterators import (
        SerialIterator,
        create_multi_node_iterator,
        create_synchronized_iterator,
    )

    comm = _comm()
    last = comm.size - 1  # a rank owned by the last process

    # root-aware object collectives: the payload must come from the
    # process owning rank `root`, not silently from process 0
    assert comm.bcast_obj(f"from-{pid}", root=last) == f"from-{nproc - 1}"
    try:
        comm.bcast_obj("x", root=comm.size)
        raise AssertionError("out-of-range root must raise")
    except ValueError:
        pass

    # multi-node iterator: per-process datasets DIFFER; the wrapped
    # stream must equal the master's (master rank on the last process)
    ds = [int(x) for x in (np.arange(8) + 1000 * pid)]
    it = create_multi_node_iterator(
        SerialIterator(ds, 4, shuffle=False), comm, rank_master=last
    )
    got = [list(it.next()) for _ in range(2)]
    want = np.arange(8) + 1000 * (nproc - 1)
    assert got[0] == list(want[:4]), got
    assert got[1] == list(want[4:]), got

    # synchronized iterator: differently-seeded iterators must agree on
    # the shuffle order after synchronization
    sit = create_synchronized_iterator(
        SerialIterator(list(range(16)), 8, shuffle=True, seed=pid), comm
    )
    order = [int(v) for v in sit.next()]
    orders = comm.allgather_obj(order)
    assert all(o == orders[0] for o in orders), orders
    assert sorted(order) != order, "shuffle should not be identity"
    return {"first_batch": [int(v) for v in got[0]]}


def scenario_allreduce_persistent(pid, nproc, scratch):
    """Per-process drifted host stats must converge to the cross-process
    mean (parity: AllreducePersistent before snapshot/eval)."""
    import numpy as np
    from chainermn_tpu.extensions.allreduce_persistent import (
        AllreducePersistent,
    )

    comm = _comm()
    arp = AllreducePersistent(comm)
    stats = {"bn": {"mean": np.full((4,), float(pid), np.float32)}}
    out = arp.reduce(stats)
    want = np.full((4,), np.mean(np.arange(nproc)), np.float32)
    np.testing.assert_allclose(np.asarray(out["bn"]["mean"]), want)
    return {}


def scenario_barrier(pid, nproc, scratch):
    """barrier() must actually rendezvous: a process arriving late must
    make the early one wait."""
    comm = _comm()
    t0 = time.monotonic()
    if pid == 1:
        time.sleep(1.5)
    comm.barrier()
    waited = time.monotonic() - t0
    if pid == 0:
        assert waited >= 1.0, f"barrier did not wait ({waited:.2f}s)"
    return {"waited": waited}


def _kill_test_pieces(comm):
    """Shared by the kill_mid_checkpoint phases: a deterministic 2-proc
    training step (closed-form oracle) + a per-rank LOCAL checkpointer.

    Loss 0.5*||w - mean(rank_values)||^2 on a replicated w: each update
    is w <- w - lr*(w - c) with c = mean over the global batch rows, so
    w after k steps has the closed form c*(1-(1-lr)^k) from w0=0 —
    every phase can recompute any step's exact params without replay.
    """
    import numpy as np
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.optimizers import build_train_step

    lr, c = 0.1, float(np.mean(np.arange(comm.size)))

    def loss_fn(params, batch):
        return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

    opt = cmn.create_multi_node_optimizer(optax.sgd(lr), comm)
    step = build_train_step(comm, loss_fn, opt, donate=False)
    params, opt_state = step.place({"w": jnp.zeros((4,))},
                                   opt.init({"w": jnp.zeros((4,))}))
    n_local = comm.size // comm.process_count
    rows = np.stack([
        np.full((4,), float(comm.process_index * n_local + i), np.float32)
        for i in range(n_local)
    ])

    def w_at(k):  # closed form
        return c * (1.0 - (1.0 - lr) ** k)

    return step, params, opt_state, rows, w_at


def scenario_kill_mid_checkpoint_phase1(pid, nproc, scratch):
    """Fault injection on the agreement protocol (VERDICT r4 #6), run A:
    both ranks train and snapshot steps 1 and 2 to PER-RANK LOCAL disk
    (the reference's storage model — npz tier); then rank 1 writes step
    3's snapshot and DIES (os._exit) before any agreement round.  Rank 0
    never has step 3.  Phase 2 (a fresh world over the same scratch)
    must agree on step 2 — the newest step present on ALL ranks."""
    import numpy as np
    import jax
    import chainermn_tpu as cmn

    comm = _comm()
    step, params, opt_state, rows, w_at = _kill_test_pieces(comm)
    ckpt = cmn.create_multi_node_checkpointer(
        "kill", comm, path=os.path.join(scratch, f"local_{pid}"),
        use_orbax=False,
    )
    for s in (1, 2):
        params, opt_state, _m = step(params, opt_state, rows)
        state = {
            "params": jax.device_get(params),
            "opt_state": jax.device_get(opt_state),
            "meta": {"it": s},
        }
        ckpt.save(s, state)
        np.testing.assert_allclose(   # sanity: oracle matches training
            np.asarray(params["w"]), np.full((4,), w_at(s)), rtol=1e-6
        )
    if pid == 1:
        # rank 1 raced ahead: its step-3 snapshot lands on ITS disk,
        # then the process dies before any cross-rank coordination —
        # exactly the window the newest-common-step protocol exists for.
        # (The step-3 params come from the closed form: the real step()
        # is a collective and rank 0 is no longer stepping.)
        w3 = {"w": np.full((4,), w_at(3), np.float32)}
        ckpt.save(3, {"params": w3, "opt_state": None, "meta": {"it": 3}})
        print("RANK1_WROTE_STEP3_AND_DIED", flush=True)
        os._exit(42)
    # rank 0 "survives" the event but is torn down with the job (a
    # graceful exit would hang in jax.distributed shutdown waiting for
    # the dead coordinator client — exactly like a real preemption,
    # where survivors are reaped too and recovery happens at RESTART,
    # which is phase 2).  It waits for rank 1's step-3 snapshot to LAND
    # first: rank 0 hosts the coordination service, and exiting while
    # rank 1 is still mid-write would kill rank 1 with the leader — a
    # harness race, not the preemption under test.
    import glob as _glob

    deadline = time.monotonic() + 60
    pattern = os.path.join(scratch, "local_1", "kill", "**",
                           "step_000000000003")
    while time.monotonic() < deadline:
        if _glob.glob(pattern, recursive=True):
            break
        time.sleep(0.05)
    print("RESULT " + json.dumps(
        {"w2": float(np.asarray(params["w"])[0])}
    ), flush=True)
    os._exit(0)


def scenario_kill_mid_checkpoint_phase2(pid, nproc, scratch):
    """Run B (restart after the kill): inventories diverge (rank 0 has
    {1,2}, rank 1 has {1,2,3}); agreement must land on step 2 = N-1,
    resume must restore step 2's exact params on BOTH ranks — rank 1's
    newer snapshot is correctly IGNORED — and training must continue
    from there (loss finite, params follow the closed form)."""
    import numpy as np
    import jax
    import chainermn_tpu as cmn

    comm = _comm()
    step, params, opt_state, rows, w_at = _kill_test_pieces(comm)
    ckpt = cmn.create_multi_node_checkpointer(
        "kill", comm, path=os.path.join(scratch, f"local_{pid}"),
        use_orbax=False,
    )
    mine = ckpt._available_steps()
    assert mine == ([1, 2] if pid == 0 else [1, 2, 3]), mine
    agreed = ckpt.newest_common_step()
    assert agreed == 2, f"agreement must pick N-1=2, got {agreed}"
    got_step, state = ckpt.resume()
    assert got_step == 2, got_step
    np.testing.assert_allclose(
        np.asarray(state["params"]["w"]), np.full((4,), w_at(2)),
        rtol=1e-6,
    )
    assert int(state["meta"]["it"]) == 2
    # training continues from the restored state: steps 3 and 4 land on
    # the closed-form trajectory
    params = jax.device_put(state["params"],
                            step.replicated_sharding)
    opt_state = jax.device_put(state["opt_state"],
                               step.replicated_sharding)
    for k in (3, 4):
        params, opt_state, m = step(params, opt_state, rows)
        np.testing.assert_allclose(
            np.asarray(params["w"]), np.full((4,), w_at(k)), rtol=1e-6
        )
        assert np.isfinite(float(m["loss"]))
    return {"resumed_step": got_step,
            "w4": float(np.asarray(params["w"])[0])}


def scenario_async_checkpoint(pid, nproc, scratch):
    """``use_async=True`` across a real 2-process world: ``save`` returns
    while the write continues on a background thread; a second save
    serializes behind the in-flight one; ``wait_until_finished`` +
    ``newest_common_step`` + ``resume`` observe the committed snapshots
    (previously async was only exercised single-process)."""
    import numpy as np
    import jax.numpy as jnp
    import chainermn_tpu as cmn

    comm = _comm()
    ckpt = cmn.create_multi_node_checkpointer(
        "amp", comm, path=os.path.join(scratch, "ckpt"), use_async=True
    )
    state2 = {
        "params": comm.bcast_data({"w": jnp.arange(8.0)}),
        "meta": {"it": 2},
    }
    ckpt.save(2, state2)
    state5 = {
        "params": comm.bcast_data({"w": jnp.arange(8.0) + 5}),
        "meta": {"it": 5},
    }
    ckpt.save(5, state5)  # must serialize behind the in-flight step-2 save
    ckpt.wait_until_finished()
    comm.barrier()  # every process committed before the agreement scan
    assert ckpt.newest_common_step() == 5
    step, restored = ckpt.resume(like=state5)
    assert step == 5, step
    np.testing.assert_allclose(
        np.asarray(restored["params"]["w"]), np.arange(8.0) + 5
    )
    assert int(np.asarray(restored["meta"]["it"])) == 5
    ckpt.finalize()
    return {"resumed_step": step}


def scenario_resilience(pid, nproc, scratch):
    """The resilience tentpole in a REAL 2-process world (faults injected
    via the CHAINERMN_TPU_FAULTS env var set by the spawning test):

    (a) an injected transient obj-store timeout (first exchange, both
        processes) is absorbed by the retry schedule — the allgather
        completes;
    (b) a NaN gradient on ONE process's rows is skipped in cross-rank
        agreement (the compiled pmin flag) — no deadlock, bit-identical
        params everywhere;
    (c) an injected mid-run failure at update call 4 (both processes)
        triggers auto-resume from ``newest_common_step()`` and training
        reaches the stop trigger with ``max_restarts`` respected.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.training.trainer import Trainer, Updater
    from chainermn_tpu.iterators import SerialIterator

    comm = _comm()

    # (a) retried obj-store exchange: the env spec fires a timeout on the
    # FIRST obj_store.exchange call of every process; the retry joins the
    # collective late (tail latency, not deadlock) and it completes.
    got = comm.allgather_obj(pid * 7)
    assert got == [i * 7 for i in range(nproc)], got

    # (b) cross-rank NaN skip agreement.
    lr, c = 0.1, float(np.mean(np.arange(comm.size)))

    def loss_fn(params, batch):
        return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

    opt = cmn.create_multi_node_optimizer(optax.sgd(lr), comm)
    step = build_train_step(comm, loss_fn, opt, donate=False,
                            nonfinite="skip")
    params, opt_state = step.place(
        {"w": jnp.zeros((4,))}, opt.init({"w": jnp.zeros((4,))})
    )
    n_local = comm.size // comm.process_count
    rows = np.stack([
        np.full((4,), float(pid * n_local + i), np.float32)
        for i in range(n_local)
    ])
    bad = rows.copy()
    if pid == 0:  # non-finite data on ONE process only
        bad[0, 0] = np.nan

    def w_at(k):
        return c * (1.0 - (1.0 - lr) ** k)

    params, opt_state, m1 = step(params, opt_state, rows)
    assert float(m1["grads_finite"]) == 1.0
    params, opt_state, m2 = step(params, opt_state, bad)
    assert float(m2["grads_finite"]) == 0.0, (
        "every rank must agree the NaN step is skipped"
    )
    np.testing.assert_allclose(  # skipped: params still at step 1
        np.asarray(params["w"]), np.full((4,), w_at(1)), rtol=1e-6
    )
    params, opt_state, m3 = step(params, opt_state, rows)
    assert float(m3["grads_finite"]) == 1.0
    flags = comm.allgather_obj(
        [float(m1["grads_finite"]), float(m2["grads_finite"]),
         float(m3["grads_finite"])]
    )
    assert all(f == flags[0] for f in flags), flags

    # (c) auto-resume across processes: train 6 iterations with a
    # per-iteration collective checkpoint; the env spec kills update
    # call 4 with a transient fault on BOTH processes (same
    # deterministic call count), so both roll back to step 3 together.
    opt2 = cmn.create_multi_node_optimizer(optax.sgd(lr), comm)
    step2 = build_train_step(comm, loss_fn, opt2, donate=False)
    p2, s2 = step2.place(
        {"w": jnp.zeros((4,))}, opt2.init({"w": jnp.zeros((4,))})
    )
    it = SerialIterator([rows[i] for i in range(n_local)], n_local,
                        shuffle=False)
    trainer = Trainer(Updater(it, step2, p2, s2),
                      stop_trigger=(6, "iteration"))
    ckpt = cmn.create_multi_node_checkpointer(
        "resume", comm, path=os.path.join(scratch, "resume_ckpt")
    )
    trainer.extend(ckpt, trigger=(1, "iteration"))
    trainer.run(max_restarts=2)
    assert trainer.iteration == 6, trainer.iteration
    assert trainer.restarts == 1, trainer.restarts
    counts = trainer.resilience_log.counts
    assert counts.get("restart") == 1, counts
    assert counts.get("fault_injected", 0) >= 1, counts
    np.testing.assert_allclose(
        np.asarray(trainer.updater.params["w"]), np.full((4,), w_at(6)),
        rtol=1e-6,
    )
    finals = comm.allgather_obj(
        float(np.asarray(trainer.updater.params["w"])[0])
    )
    assert all(abs(f - finals[0]) < 1e-6 for f in finals), finals
    return {"final_w": finals[0], "restarts": trainer.restarts}


def scenario_wire_int8(pid, nproc, scratch):
    """ISSUE 4 satellite: the bucketed+int8 gradient wire end to end in
    a real 2-process world, under the fault injector.

    The spawning test sets CHAINERMN_TPU_FAULTS to truncate the FIRST
    ``obj_store.exchange`` payload on every process: each process
    truncates its *own* outgoing plan-hash payload, so every process
    observes the corruption (`PayloadCorruptionError`) and retries the
    exchange in lockstep — the collective stream stays aligned, the
    retry's clean exchange agrees on the plan hash, and the compiled
    int8+error-feedback run completes with bit-identical params on all
    processes.
    """
    import numpy as np
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.comm_wire import (
        WireConfig, plan_agreement, plan_of_tree,
    )
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.resilience import fault_injection as fi

    comm = _comm()
    rng = np.random.RandomState(0)  # same seed -> same model everywhere
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
    }
    wire = WireConfig(codec="int8", error_feedback=True)

    # plan agreement: the first exchange carries a truncated payload ->
    # PayloadCorruptionError -> retried -> every process agrees
    plan = plan_of_tree(params, wire.bucket_bytes, wire.max_buckets)
    agreed = plan_agreement(comm, plan)
    assert agreed == plan.plan_hash()
    inj = fi.active()
    assert inj is not None, "fault injector must be env-activated"
    assert inj.log.counts.get("fault_injected", 0) >= 1, (
        "the truncate fault must have fired before the retry succeeded"
    )

    # compiled bucketed+int8+EF training across the 2-process mesh
    w_true = rng.randn(8, 4).astype(np.float32)
    x_all = rng.randn(16, 8).astype(np.float32)
    y_all = x_all @ w_true

    def loss_fn(p, b):
        bx, by = b
        h = jnp.tanh(bx @ p["w1"])
        return jnp.mean((h @ p["w2"] - by) ** 2)

    opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm,
                                          wire=wire)
    step = build_train_step(comm, loss_fn, opt, donate=False)
    p, o = step.place(params, opt.init(params))
    lo = pid * (16 // nproc)  # per-process slice of the global batch
    hi = lo + 16 // nproc
    batch = (x_all[lo:hi], y_all[lo:hi])
    first = last = None
    for _ in range(20):
        p, o, m = step(p, o, batch)
        last = float(m["loss"])
        if first is None:
            first = last
    assert last < first, (first, last)
    assert isinstance(o.wire_residual, tuple) and o.wire_residual

    # bit-identical replicated params on every process (sha256, not
    # hash(): bytes hashing is salted per process)
    import hashlib

    digests = comm.allgather_obj(hashlib.sha256(
        b"".join(np.asarray(p[k]).tobytes() for k in sorted(p))
    ).hexdigest())
    assert all(d == digests[0] for d in digests), digests
    return {"first_loss": first, "final_loss": last,
            "faults": inj.log.counts.get("fault_injected", 0)}


def scenario_overlap_fault(pid, nproc, scratch):
    """ISSUE 8 satellite: the overlap-scheduled compiled step in a real
    2-process world, under the fault injector.

    The spawning test truncates the plan-agreement AND trace-guard
    exchanges (``obj_store.exchange`` calls #1/#3) on every process:
    each transient is observed by every rank in lockstep, retried, and
    — the point of this scenario — the retry must not reorder or drop
    any of the overlapped program's buckets.  Pinned three ways:

    * the overlap step's collective trace hash, re-derived AFTER the
      faulted run, equals the pre-run hash and agrees across ranks
      (nothing reordered);
    * every bucket psum still issues at its dependency frontier
      (``analysis.check_overlap`` returns no findings);
    * the loss trajectory and final params are BIT-IDENTICAL to the
      synchronous (overlap="none") run of the same world with no fault
      in flight (the injected faults are call-count-addressed to the
      overlap run's exchanges only).
    """
    import hashlib

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.analysis import check_overlap
    from chainermn_tpu.comm_wire import WireConfig, plan_of_tree
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.resilience import fault_injection as fi

    comm = _comm()
    rng = np.random.RandomState(0)
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
        "w3": jnp.asarray(rng.randn(4, 4) * 0.3, jnp.float32),
    }
    # tiny buckets -> one per leaf: a genuinely multi-bucket program
    wire = WireConfig(codec="none", bucket_bytes=64, max_buckets=0)
    w_true = rng.randn(8, 4).astype(np.float32)
    x_all = rng.randn(16, 8).astype(np.float32)
    y_all = x_all @ w_true

    def loss_fn(p, b):
        bx, by = b
        h = jnp.tanh(bx @ p["w1"])
        return jnp.mean(((h @ p["w2"]) @ p["w3"] - by) ** 2)

    lo = pid * (16 // nproc)
    hi = lo + 16 // nproc
    batch = (x_all[lo:hi], y_all[lo:hi])

    def run(overlap):
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05), comm, wire=wire, overlap=overlap
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        pre_hash = step.collective_trace(p, o, batch).trace_hash()
        losses = []
        for _ in range(10):
            p, o, m = step(p, o, batch)
            losses.append(float(m["loss"]))
        post_hash = step.collective_trace(p, o, batch).trace_hash()
        return step, p, o, pre_hash, post_hash, losses

    # overlap run first: its exchanges (plan agreement = exchange #1,
    # trace guard = #3) absorb the injected truncations
    step_b, p_b, o_b, pre_b, post_b, losses_b = run("bucket")
    inj = fi.active()
    assert inj is not None, "fault injector must be env-activated"
    assert inj.log.counts.get("fault_injected", 0) >= 2, (
        "both injected truncations must have fired",
        dict(inj.log.counts),
    )
    # retried transients did not reorder the program
    assert pre_b == post_b
    hashes = comm.allgather_obj(post_b)
    assert all(h == hashes[0] for h in hashes), hashes
    # ...and did not drop a bucket: every bucket psum still issues at
    # its dependency frontier
    plan = plan_of_tree(params, wire.bucket_bytes, wire.max_buckets)
    assert plan.n_buckets >= 3
    # inspect the variant the faulted run actually EXECUTED: the step
    # places the per-process local rows into the global batch before
    # dispatch, and OverlappedStep caches per aval signature — handing
    # it the raw local batch would trace (and validate) a different,
    # never-run variant
    placed_batch = step_b.place_batch(batch)
    jb = step_b.get_jitted(p_b, o_b).scheduled_jaxpr(
        p_b, o_b, placed_batch
    )
    findings = check_overlap(jb, plan)
    assert not findings, [str(f) for f in findings]

    # no-fault synchronous reference: bit-identical losses and params
    step_s, p_s, o_s, pre_s, post_s, losses_s = run("none")
    assert losses_b == losses_s, (losses_b, losses_s)
    assert pre_b != pre_s  # ordering genuinely moved vs sync
    for k in sorted(params):
        np.testing.assert_array_equal(
            np.asarray(p_b[k]), np.asarray(p_s[k])
        )
    digests = comm.allgather_obj(hashlib.sha256(
        b"".join(np.asarray(p_b[k]).tobytes() for k in sorted(p_b))
    ).hexdigest())
    assert all(d == digests[0] for d in digests), digests
    return {
        "faults": inj.log.counts.get("fault_injected", 0),
        "final_loss": losses_b[-1],
        "buckets": plan.n_buckets,
    }


def scenario_multihop_fault(pid, nproc, scratch):
    """ISSUE 11 satellite: the hier_rs_ag multi-hop wire in a REAL
    2-proc hierarchical world (2 processes x 2 local CPU devices: the
    process grouping IS the slice grouping, so the mesh genuinely
    factorizes ('mn_inter', 'mn_intra') = (2, 2)), under the fault
    injector.

    The spawning test truncates ``obj_store.exchange`` calls #1 and #3
    on every process — the standalone schedule/plan agreement below and
    the one ``opt.init`` re-runs inside the training run: each torn
    payload is observed by every rank in lockstep, retried, and the
    multi-hop program must come through untouched —

    * the agreed WirePlan hash covers bucket layout AND per-bucket
      schedule, and every rank lands on the same one;
    * the step's collective trace carries the full rs→ar→ag triple per
      hier bucket, hashes identically before and after the faulted run,
      and agrees across ranks;
    * the loss trajectory and final params are BIT-IDENTICAL to a
      no-fault run of the same schedule (the injected faults are
      call-count-addressed to the first run's exchanges only).
    """
    import hashlib

    import numpy as np
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.comm_wire import WireConfig, plan_agreement, plan_wire
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.resilience import fault_injection as fi

    comm = _comm("hierarchical")
    assert dict(comm.mesh.shape) == {"mn_inter": nproc,
                                     "mn_intra": comm.size // nproc}, (
        dict(comm.mesh.shape)
    )
    rng = np.random.RandomState(0)  # same seed -> same model everywhere
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
        "w3": jnp.asarray(rng.randn(4, 4) * 0.3, jnp.float32),
    }
    # tiny buckets -> one per leaf: a genuinely multi-bucket multi-hop
    # program (every bucket staged rs -> ar -> ag)
    wire = WireConfig(schedule="hier_rs_ag", bucket_bytes=64,
                      max_buckets=0)

    # schedule/plan agreement: the first exchange carries a truncated
    # payload -> PayloadCorruptionError on EVERY rank -> lockstep retry
    # -> every rank agrees on layout AND schedule
    wplan = plan_wire(params, wire, comm.mesh)
    assert set(wplan.schedules) == {"hier_rs_ag"}, wplan.schedules
    agreed = plan_agreement(comm, wplan)
    assert agreed == wplan.plan_hash()
    inj = fi.active()
    assert inj is not None, "fault injector must be env-activated"
    assert inj.log.counts.get("fault_injected", 0) >= 1, (
        "the truncate fault must have fired before the retry succeeded"
    )

    w_true = rng.randn(8, 4).astype(np.float32)
    x_all = rng.randn(16, 8).astype(np.float32)
    y_all = x_all @ w_true

    def loss_fn(p, b):
        bx, by = b
        h = jnp.tanh(bx @ p["w1"])
        return jnp.mean(((h @ p["w2"]) @ p["w3"] - by) ** 2)

    lo = pid * (16 // nproc)
    hi = lo + 16 // nproc
    batch = (x_all[lo:hi], y_all[lo:hi])

    def run():
        opt = cmn.create_multi_node_optimizer(
            optax.sgd(0.05), comm, wire=wire
        )
        step = build_train_step(comm, loss_fn, opt, donate=False)
        p, o = step.place(params, opt.init(params))
        pre_hash = step.collective_trace(p, o, batch).trace_hash()
        losses = []
        for _ in range(10):
            p, o, m = step(p, o, batch)
            losses.append(float(m["loss"]))
        post_hash = step.collective_trace(p, o, batch).trace_hash()
        return step, p, o, pre_hash, post_hash, losses

    # faulted run first: opt.init's plan-agreement exchange is call #3
    # and absorbs the second injected truncation
    step_a, p_a, o_a, pre_a, post_a, losses_a = run()
    assert inj.log.counts.get("fault_injected", 0) >= 2, (
        "both injected truncations must have fired",
        dict(inj.log.counts),
    )
    # retried transients did not reorder or drop a hop
    assert pre_a == post_a
    hashes = comm.allgather_obj(post_a)
    assert all(h == hashes[0] for h in hashes), hashes
    tr = step_a.collective_trace(p_a, o_a, batch)
    n_buckets = wplan.n_buckets
    assert n_buckets >= 3
    census = tr.census()
    assert census.get("reduce_scatter", 0) == n_buckets, census
    assert census.get("all_gather", 0) == n_buckets, census
    assert census.get("all_reduce", 0) == n_buckets + 1, census

    # no-fault reference run of the same schedule: bit-identical
    step_b, p_b, o_b, pre_b, post_b, losses_b = run()
    assert losses_a == losses_b, (losses_a, losses_b)
    for k in sorted(params):
        np.testing.assert_array_equal(
            np.asarray(p_a[k]), np.asarray(p_b[k])
        )
    digests = comm.allgather_obj(hashlib.sha256(
        b"".join(np.asarray(p_a[k]).tobytes() for k in sorted(p_a))
    ).hexdigest())
    assert all(d == digests[0] for d in digests), digests
    return {
        "faults": inj.log.counts.get("fault_injected", 0),
        "final_loss": losses_a[-1],
        "buckets": n_buckets,
        "mesh": dict(comm.mesh.shape),
    }


def scenario_tuned_wire_fault(pid, nproc, scratch):
    """ISSUE 12 satellite: the measured-feedback autotuner in a REAL
    2-proc hierarchical world (2 processes x 2 local CPU devices —
    process grouping = slice grouping, mesh (2, 2)).

    Phase A — shared profile under faults: rank 0 writes ONE
    BandwidthProfile file (atomic rename) into the shared scratch, both
    ranks load it through ``create_multi_node_optimizer(profile=path)``.
    The spawning test truncates obj-store exchanges #1 and #3 (the
    standalone plan agreement below and the one ``opt.init`` re-runs):
    each torn payload surfaces on every rank in lockstep, is retried,
    and the tuned plan comes through with the profile hash folded into
    the agreed ``WirePlan.plan_hash()`` — identical on every rank.  The
    profile's slow-inter/fast-intra curves stage every bucket, so the
    trace must carry the rs→ar→ag triple per bucket, and a short
    training run completes with bit-identical digests across ranks.

    Phase B — mismatched profile: rank 1 swaps in a perturbed profile
    (one bandwidth point changed -> different content hash).  A fresh
    optimizer's ``init`` must raise ``WirePlanMismatchError`` on BOTH
    ranks BEFORE any collective — the schedules may even coincide on
    this model; the hash-folded profile is what guarantees the
    divergence is caught now rather than on the first model where the
    decisions split.
    """
    import hashlib

    import numpy as np
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.comm_wire import (
        BandwidthProfile, WireConfig, WirePlanMismatchError,
        plan_agreement,
    )
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.resilience import fault_injection as fi

    comm = _comm("hierarchical")
    assert dict(comm.mesh.shape) == {"mn_inter": nproc,
                                     "mn_intra": comm.size // nproc}, (
        dict(comm.mesh.shape)
    )

    def make_profile(inter_bw):
        # slow inter, fast intra: the measured decision stages every
        # bucket (predicted hier time beats the flat psum for any
        # payload on these curves)
        return BandwidthProfile(
            mesh_axes=tuple(dict(comm.mesh.shape).items()),
            curves={
                ("inter", "all_reduce"): [(64, inter_bw),
                                          (1 << 22, inter_bw)],
                ("intra", "all_reduce"): [(64, 1e12), (1 << 22, 1e12)],
                ("intra", "reduce_scatter"): [(64, 1e12),
                                              (1 << 22, 1e12)],
                ("intra", "all_gather"): [(64, 1e12), (1 << 22, 1e12)],
                ("mixed", "all_reduce"): [(64, inter_bw),
                                          (1 << 22, inter_bw)],
            },
            latency={"inter": 1e-9, "intra": 1e-9, "mixed": 1e-9},
            label="tuned_wire_fault",
        )

    profile_path = os.path.join(scratch, "wire_profile.json")
    if pid == 0:
        tmp = profile_path + ".tmp"
        make_profile(1e6).save(tmp)
        os.replace(tmp, profile_path)  # readers never see a torn file
    deadline = time.time() + 60
    while not os.path.exists(profile_path):
        if time.time() > deadline:
            raise RuntimeError("rank 0 never published the profile")
        time.sleep(0.05)

    rng = np.random.RandomState(0)  # same seed -> same model everywhere
    params = {
        "w1": jnp.asarray(rng.randn(8, 16) * 0.3, jnp.float32),
        "w2": jnp.asarray(rng.randn(16, 4) * 0.3, jnp.float32),
        "w3": jnp.asarray(rng.randn(4, 4) * 0.3, jnp.float32),
    }
    # tiny buckets -> one per leaf: a genuinely multi-bucket tuned
    # program; schedule="auto" so the PROFILE (not a forced knob) is
    # what stages the buckets
    wire = WireConfig(bucket_bytes=64, max_buckets=0)

    opt0 = cmn.create_multi_node_optimizer(
        optax.sgd(0.05), comm, wire=wire, profile=profile_path
    )
    wplan = opt0.wire_plan(params)
    assert set(wplan.schedules) == {"hier_rs_ag"}, wplan.schedules
    assert wplan.profile_hash == opt0.profile.profile_hash()

    # exchange #1 (truncated -> lockstep retry): the agreed hash covers
    # layout AND schedules AND the profile content hash
    agreed = plan_agreement(comm, wplan)
    assert agreed == wplan.plan_hash()
    inj = fi.active()
    assert inj is not None, "fault injector must be env-activated"
    assert inj.log.counts.get("fault_injected", 0) >= 1, (
        "the truncate fault must have fired before the retry succeeded"
    )

    w_true = rng.randn(8, 4).astype(np.float32)
    x_all = rng.randn(16, 8).astype(np.float32)
    y_all = x_all @ w_true

    def loss_fn(p, b):
        bx, by = b
        h = jnp.tanh(bx @ p["w1"])
        return jnp.mean(((h @ p["w2"]) @ p["w3"] - by) ** 2)

    lo = pid * (16 // nproc)
    hi = lo + 16 // nproc
    batch = (x_all[lo:hi], y_all[lo:hi])

    # the training run: opt.init's plan-agreement exchange is obj-store
    # call #3 and absorbs the second injected truncation
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(0.05), comm, wire=wire, profile=profile_path
    )
    step = build_train_step(comm, loss_fn, opt, donate=False)
    p, o = step.place(params, opt.init(params))
    assert inj.log.counts.get("fault_injected", 0) >= 2, (
        "both injected truncations must have fired",
        dict(inj.log.counts),
    )
    losses = []
    for _ in range(5):
        p, o, m = step(p, o, batch)
        losses.append(float(m["loss"]))
    tr = step.collective_trace(p, o, batch)
    census = tr.census()
    n_buckets = wplan.n_buckets
    assert n_buckets >= 3
    assert census.get("reduce_scatter", 0) == n_buckets, census
    assert census.get("all_gather", 0) == n_buckets, census
    assert census.get("all_reduce", 0) == n_buckets + 1, census
    hashes = comm.allgather_obj(tr.trace_hash())
    assert all(h == hashes[0] for h in hashes), hashes
    digests = comm.allgather_obj(hashlib.sha256(
        b"".join(np.asarray(p[k]).tobytes() for k in sorted(p))
    ).hexdigest())
    assert all(d == digests[0] for d in digests), digests

    # phase B: rank 1 tunes from a PERTURBED profile — both ranks must
    # raise WirePlanMismatchError at init, before any collective
    my_profile = (
        make_profile(2e6) if pid == 1
        else BandwidthProfile.load(profile_path)
    )
    opt_bad = cmn.create_multi_node_optimizer(
        optax.sgd(0.05), comm, wire=wire, profile=my_profile
    )
    mismatch_raised = False
    try:
        opt_bad.init(params)
    except WirePlanMismatchError:
        mismatch_raised = True
    assert mismatch_raised, (
        "mismatched profiles must fail plan agreement on every rank"
    )
    return {
        "faults": inj.log.counts.get("fault_injected", 0),
        "final_loss": losses[-1],
        "buckets": n_buckets,
        "mesh": dict(comm.mesh.shape),
        "profile_hash": wplan.profile_hash,
        "plan_hash": agreed,
        "mismatch_raised": mismatch_raised,
    }


def scenario_trace_divergence(pid, nproc, scratch):
    """ISSUE 5 satellite: two processes build INTENTIONALLY divergent
    train steps (the rank named by CHAINERMN_TPU_DIVERGE_RANK adds one
    extra psum to its loss), and the collective divergence guard —
    wired into build_train_step's first dispatch — raises the
    non-recoverable ``CollectiveTraceMismatchError`` on BOTH ranks
    before any device collective runs.  Without the guard this world
    deadlocks at the first mis-paired collective (the spawning test's
    timeout is the regression detector for that)."""
    import numpy as np
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.functions import collectives as cc
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.resilience.errors import CollectiveTraceMismatchError

    comm = _comm()
    diverge_rank = int(os.environ["CHAINERMN_TPU_DIVERGE_RANK"])

    def loss_fn(params, batch):
        l = 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)
        if pid == diverge_rank:
            # the divergent collective: an extra (value-neutral) psum
            # only THIS rank's program contains
            l = l + 0.0 * cc.psum(l, comm.axis_names)
        return l

    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1), comm)
    params = {"w": jnp.zeros((4,))}
    step = build_train_step(comm, loss_fn, opt, donate=False)
    # opt.init's wire-plan agreement PASSES (same shapes everywhere);
    # only the collective TRACE diverges — exactly the gap ISSUE 5's
    # guard exists to close
    p, o = step.place(params, opt.init(params))
    n_local = comm.size // comm.process_count
    rows = np.zeros((n_local, 4), np.float32)
    try:
        step(p, o, rows)
    except CollectiveTraceMismatchError as e:
        assert e.recoverable is False
        return {"raised": type(e).__name__,
                "hash_len": len(step.collective_trace(
                    p, o, rows).trace_hash())}
    raise AssertionError(
        "divergence guard did not fire on a divergent world"
    )


def scenario_protocol_divergence(pid, nproc, scratch):
    """ISSUE 20: the HOST-protocol guard fires on every rank before a
    divergent control plane can deadlock.

    Phase 1 proves the guard rides the lockstep retry: symmetric
    obj-store traffic, then ``protocol_agreement`` with a truncate
    fault injected on the guard's OWN agreement exchange — every
    process observes the torn payload (``PayloadCorruptionError``),
    every process retries together, and the agreement succeeds with
    identical hashes.

    Phase 2 diverges the protocol two ways at once: the rank named by
    CHAINERMN_TPU_DIVERGE_RANK issues an EXTRA obj-store ``send_obj``
    (a non-blocking KV publish — deliberately chosen so the world is
    still alive for the guard; an extra host *collective* would
    deadlock at transport before any check could run), and the two
    ranks issue their two lockstep agreement sites in OPPOSITE order
    (transport still pairs — both run two allgathers — but the ordered
    site tokens differ).  ``protocol_agreement`` must raise the
    non-recoverable ``ProtocolDivergenceError`` on BOTH ranks."""
    from chainermn_tpu.analysis.checks import protocol_agreement
    from chainermn_tpu.resilience import fault_injection as fi
    from chainermn_tpu.resilience import protocol as proto
    from chainermn_tpu.resilience.errors import ProtocolDivergenceError
    from chainermn_tpu.resilience.retry import lockstep_allgather

    # install BEFORE the communicator so world-formation exchanges are
    # recorded symmetrically on every rank (launcher sets the env)
    rec = proto.install_from_env(label=f"protodiv_p{pid}", rank=pid,
                                 world=nproc)
    assert rec is not None, "CHAINERMN_TPU_PROTOCOL_RECORD must be set"
    comm = _comm()
    diverge = int(os.environ["CHAINERMN_TPU_DIVERGE_RANK"])

    # -- phase 1: symmetric traffic; torn payload on the guard itself --
    comm.send_obj({"pid": pid}, dest=(pid + 1) % nproc, tag=7)
    got = comm.recv_obj(source=(pid - 1) % nproc, tag=7)
    assert got == {"pid": (pid - 1) % nproc}, got
    lockstep_allgather(comm, pid, site="mp.protocol.phase1")
    with fi.inject_faults([
        fi.FaultSpec("obj_store.exchange", "truncate", at=[1])
    ]):
        # each process truncates its own outgoing agreement payload on
        # attempt 1; ALL observe the corruption, ALL retry in lockstep
        h1 = protocol_agreement(comm, label="phase1")
        inj = fi.active()
        assert inj.log.counts.get("fault_injected", 0) >= 1, (
            "the truncate fault must have fired on the guard's exchange"
        )

    # -- phase 2: one extra KV publish + swapped agreement-site order --
    if pid == diverge:
        comm.send_obj({"extra": True}, dest=(pid + 1) % nproc, tag=6)
    sites = ["mp.protocol.siteA", "mp.protocol.siteB"]
    if pid == diverge:
        sites.reverse()
    for s in sites:
        lockstep_allgather(comm, pid, site=s)
    try:
        protocol_agreement(comm, label="phase2")
    except ProtocolDivergenceError as e:
        assert e.recoverable is False
        # export for the FleetReport merge the spawning test asserts on
        rec.to_jsonl(os.path.join(
            scratch, f"protodiv_p{pid}_protocol.jsonl"
        ))
        return {"raised": type(e).__name__, "phase1": h1,
                "entries": len(rec)}
    raise AssertionError(
        "host-protocol guard did not fire on a divergent world"
    )


def scenario_mismatched_sharding(pid, nproc, scratch):
    """ISSUE 6 satellite: rank 1 is handed a MISMATCHED input sharding
    (row-sharded where every other rank declares replicated), so its
    compiled program carries partitioner-inserted all-gathers the
    author never wrote.  The ``implicit_collectives`` check — its
    cross-process form ``implicit_agreement`` — exchanges per-rank
    implicit counts over the host control plane and raises
    ``ImplicitCollectiveError`` on BOTH ranks before any dispatch, with
    an equation-level citation naming the responsible dot_general."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from chainermn_tpu.analysis import (
        ImplicitCollectiveError,
        implicit_agreement,
        shardflow,
        trace_collectives,
    )

    comm = _comm()
    mismatch_rank = int(os.environ["CHAINERMN_TPU_MISMATCH_RANK"])

    def f(x):
        return x @ x.T

    # the mismatched rank shards rows into a program whose matmul the
    # partitioner can only resolve by gathering; everyone else runs the
    # replicated (collective-free) program
    spec = P("mn", None) if pid == mismatch_rank else P()
    jitted = jax.jit(
        f,
        in_shardings=NamedSharding(comm.mesh, spec),
        out_shardings=NamedSharding(comm.mesh, P()),
    )
    sds = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    txt = jitted.lower(sds).compile().as_text()  # static — no dispatch
    tr = trace_collectives(f, sds)
    flow = shardflow(f, sds, in_specs=(spec,), out_specs=(P(),))
    assert len(tr) == 0  # nothing authored — any HLO collective is implicit
    try:
        implicit_agreement(comm, tr, txt, flow=flow, label="mismatched")
    except ImplicitCollectiveError as e:
        msg = str(e)
        assert f"rank {mismatch_rank}" in msg, msg
        # equation-level citation from the XLA metadata / flow pass
        assert "dot_general" in msg, msg
        return {"raised": type(e).__name__,
                "cited_dot": "dot_general" in msg}
    raise AssertionError(
        "implicit_collectives agreement did not fire on a world with a "
        "mismatched input sharding"
    )


def _spot_reclaim_pieces(comm, scratch, lr=0.1, mom=0.9):
    """Shared by the spot_reclaim phases: a ZeRO (sgd+momentum) world
    whose momentum state is BLOCKED over the ranks — the state that must
    genuinely reshard N→M — plus the shared-FS orbax checkpointer.

    Loss 0.5*||w - batch.mean||^2 with global batch rows {0, 1}: the
    gradient is elementwise w - 0.5 at EVERY world size that feeds the
    same global rows, so the single-world trajectory (a numpy simulation
    of sgd+momentum from w0=0) is the oracle for any resize point."""
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu.optimizers import build_train_step

    def loss_fn(params, batch):
        return 0.5 * jnp.sum((params["w"] - batch.mean(axis=0)) ** 2)

    opt = cmn.create_multi_node_optimizer(
        optax.sgd(lr, momentum=mom), comm, zero_redundancy=True
    )
    step = build_train_step(comm, loss_fn, opt, donate=False)
    ckpt = cmn.create_multi_node_checkpointer(
        "spot", comm, path=os.path.join(scratch, "spot_ckpt")
    )
    return opt, step, ckpt


def _spot_oracle(n_steps, lr=0.1, mom=0.9, c=0.5, dim=4):
    """Numpy simulation of the same sgd+momentum math, world-free."""
    import numpy as np

    w = np.zeros(dim)
    v = np.zeros(dim)
    traj = []
    for _ in range(n_steps):
        g = w - c
        v = mom * v + g
        w = w - lr * v
        traj.append(w.copy())
    return traj


def scenario_spot_reclaim_phase1(pid, nproc, scratch):
    """ISSUE 7 satellite, run A (the reclaim): a 2-proc ZeRO world
    (momentum state blocked (2, k) over the ranks) trains and
    collectively snapshots steps 1-3 — each save writes the world
    manifest (world_size=2) beside the orbax dir.  Update 4 then begins
    and the fault injector preempts worker 1 at the ``trainer.update``
    site (env-injected ``die`` spec targeted at process 1) BEFORE it
    dispatches: a spot reclaim mid-step.  Worker 0's slice is gone with
    it — real preemption reaps the survivors too, and recovery happens
    at RESTART (phase 2, world size 1)."""
    import numpy as np
    import jax.numpy as jnp
    from chainermn_tpu.resilience import fault_injection as fi

    comm = _comm()
    opt, step, ckpt = _spot_reclaim_pieces(comm, scratch)
    p0 = {"w": jnp.zeros((4,))}
    params, opt_state = step.place(p0, opt.init(p0))
    rows = np.full((1, 4), float(pid), np.float32)  # global rows {0, 1}
    oracle = _spot_oracle(3)
    for s in (1, 2, 3):
        fi.fire("trainer.update")
        params, opt_state, _m = step(params, opt_state, rows)
        ckpt.save(s, {
            "params": params,
            "opt_state": opt_state,
            "trainer": {"iteration": s, "iterator": None},
        })
        np.testing.assert_allclose(  # sanity: ZeRO matches the oracle
            np.asarray(params["w"]), oracle[s - 1], rtol=1e-5
        )
    # update 4 begins; the injector reclaims worker 1 here (die,
    # process-targeted) — worker 0 is reaped with the job by design.
    # Worker 0 (the coordination-service host) lingers briefly so the
    # reclaim lands before the leader disappears (worker 1's remaining
    # path after the save barrier is fire -> os._exit, sub-ms).
    fi.fire("trainer.update")
    if pid == 0:
        time.sleep(1.0)
    print("RESULT " + json.dumps({"steps_saved": 3}), flush=True)
    os._exit(0)


def scenario_spot_reclaim_phase2(pid, nproc, scratch):
    """Run B (the elastic restart): world size 1 re-forms via
    ``Trainer.run_elastic``; the elected snapshot's manifest names world
    2, so ``resume`` routes through the resharder — the momentum blocks
    re-partition (2, 2) -> (1, 4) bit-identically to a fresh partition
    of the gathered global state — and training continues steps 4-6.
    The loss trajectory after resume must land on the single-world
    oracle (the same sgd+momentum math simulated in numpy over all 6
    steps with no interruption)."""
    import warnings

    import numpy as np
    import jax.numpy as jnp
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.training.trainer import Trainer, Updater

    assert nproc == 1
    rows = [np.full((4,), 0.0, np.float32),
            np.full((4,), 1.0, np.float32)]  # the FULL global batch now

    def build(comm):
        opt, step, ckpt = _spot_reclaim_pieces(comm, scratch)
        p0 = {"w": jnp.zeros((4,))}
        params, opt_state = step.place(p0, opt.init(p0))
        it = SerialIterator(rows, 2, shuffle=False)
        trainer = Trainer(Updater(it, step, params, opt_state),
                          stop_trigger=(6, "iteration"))
        trainer.extend(ckpt, trigger=(1, "iteration"))
        return trainer

    with warnings.catch_warnings():
        # the resharder warns (by design) about the reset trainer
        # template slots the manual phase-1 saves did not carry
        warnings.simplefilter("ignore")
        trainer = Trainer.run_elastic(build, communicator_name="tpu")

    ev = trainer.resilience_log.events("elastic_restart")
    assert ev and ev[0].info["restored_step"] == 3, ev
    resized = ev[0].info["resized"]
    assert tuple(resized) == (2, 1), resized
    assert trainer.iteration == 6, trainer.iteration
    oracle = _spot_oracle(6)
    got = np.asarray(trainer.updater.params["w"])
    ok = bool(np.allclose(got, oracle[5], rtol=1e-5))
    assert ok, (got, oracle[5])
    return {"resumed_step": ev[0].info["restored_step"],
            "resized": list(resized),
            "oracle_match": ok,
            "final_w": float(got[0])}


def _serving_fixture():
    """Shared by the serving_churn phases: a deterministic tiny LM
    (same seed on every process -> identical params -> greedy decode
    of any request is bit-identical no matter WHICH replica runs it)
    and the scripted request stream."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from chainermn_tpu.models.transformer import TransformerLM

    model = TransformerLM(vocab_size=64, d_model=32, n_heads=4,
                          n_layers=2, max_len=64)
    params = model.init(
        {"params": jax.random.PRNGKey(0),
         "dropout": jax.random.PRNGKey(1)},
        jnp.zeros((1, 8), jnp.int32),
    )
    rng = np.random.RandomState(5)
    stream = [
        ("c%d" % i, rng.randint(0, 64, int(rng.randint(3, 10))).tolist(),
         6)
        for i in range(8)
    ]
    return model, params, stream


def _serving_engine(model, params):
    from chainermn_tpu.serving.decode import DecodeEngine

    return DecodeEngine(model, params, capacity=2, page_size=8)


def scenario_serving_churn_phase1(pid, nproc, scratch):
    """ISSUE 13 satellite, run A (the churn): two single-process decode
    replicas share one journal directory and partition a scripted
    8-request stream by submission seq.  The fault injector kills
    replica 1 (process-targeted ``die`` at the ``serving.decode_step``
    site) mid-stream — a hard reclaim, no drain.  Replica 0 completes
    its own share; replica 1's unserved requests stay journaled
    (results are atomic files, so no torn result can exist).  Recovery
    happens at restart (phase 2, world size 1)."""
    from chainermn_tpu.serving.batcher import Request
    from chainermn_tpu.serving.replica import DecodeReplica, RequestJournal

    model, params, stream = _serving_fixture()
    journal = RequestJournal(os.path.join(scratch, "serve_journal"))
    if pid == 0:
        journal.submit_all([
            Request(p, m, id=i) for i, p, m in stream
        ])
    # journal-level rendezvous (no collectives: a dead peer must not
    # wedge the survivor) — wait until the full stream is visible
    deadline = time.monotonic() + 60
    while len(journal.requests()) < len(stream):
        if time.monotonic() > deadline:
            raise RuntimeError("journal never filled")
        time.sleep(0.05)
    replica = DecodeReplica(
        _serving_engine(model, params), journal,
        replica_index=pid, n_replicas=nproc,
    )
    served = replica.serve()  # process 1 dies inside (env fault spec)
    # replica 0 (the coordination-service host) lingers so the targeted
    # kill lands before the leader disappears, then exits hard —
    # jax.distributed teardown would block on the dead peer
    print("RESULT " + json.dumps(
        {"served": sorted(served), "replica": pid}
    ), flush=True)
    time.sleep(1.0)
    os._exit(0)


def scenario_serving_churn_phase2(pid, nproc, scratch):
    """Run B (the elastic completion): the surviving world re-forms at
    replica count 1 via ``serve_elastic`` — the pending partition
    re-derives over ONE replica, so the dead replica's share migrates —
    and every journaled request completes with outputs BIT-IDENTICAL
    to a no-fault run (greedy decode is deterministic in the request,
    not in the replica that runs it: pinned here by comparing every
    result against a fresh in-process oracle engine)."""
    from chainermn_tpu.serving.replica import RequestJournal, serve_elastic

    assert nproc == 1
    model, params, stream = _serving_fixture()
    journal = RequestJournal(os.path.join(scratch, "serve_journal"))
    pending_before = len(journal.pending())
    assert pending_before > 0, (
        "phase 1's kill should have left unserved requests"
    )

    def build(comm):
        from chainermn_tpu.serving.replica import DecodeReplica

        return DecodeReplica(
            _serving_engine(model, params), journal,
            replica_index=0, n_replicas=1,
        )

    replica = serve_elastic(
        build, os.path.join(scratch, "serve_journal"),
        communicator_name="tpu", replica_index=0, n_replicas=1,
    )
    assert len(journal.pending()) == 0
    results = journal.results()
    assert sorted(results) == sorted(i for i, _p, _m in stream)
    # the no-fault oracle: every request decoded directly
    oracle_eng = _serving_engine(model, params)
    mismatches = []
    for rid, prompt, max_new in stream:
        want = oracle_eng.generate(prompt, max_new)
        if results[rid]["tokens"] != want:
            mismatches.append(rid)
    assert not mismatches, mismatches
    ev = replica.batcher.engine  # engine served at least the migrated share
    return {
        "pending_before": pending_before,
        "completed": len(results),
        "bit_identical": True,
        "survivor_steps": int(ev.steps),
    }


def scenario_telemetry(pid, nproc, scratch):
    """ISSUE 10 satellite: runtime telemetry in a REAL 2-process world
    (faults via CHAINERMN_TPU_FAULTS set by the spawning test):

    (a) an injected obj-store timeout on the FIRST exchange is absorbed
        by the lockstep retry — and both the fault and its retry land
        in the exported timeline, in order;
    (b) a delay fault at ``trainer.update`` TARGETED at process 1 makes
        it the straggler: the cross-rank ``MetricsReport`` (allgathered
        phase summaries) flags process 1 on BOTH ranks;
    (c) a process-local eager bucketed allreduce_grad contributes
        per-bucket ``collective.psum`` spans to the same stream;
    (d) the merged Chrome-trace/JSONL export validates: step spans,
        bucket collective spans, and resilience events in one
        time-ordered timeline.
    """
    import json as _json

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    import chainermn_tpu as cmn
    from chainermn_tpu import observability as obs
    from chainermn_tpu.optimizers import build_train_step
    from chainermn_tpu.training.trainer import Trainer, Updater
    from chainermn_tpu.iterators import SerialIterator
    from chainermn_tpu.resilience.log import (
        ResilienceLog, attach, detach,
    )

    tel = obs.Telemetry(label=f"proc{pid}")
    obs.install(tel)
    slog = ResilienceLog()  # catches emits outside trainer.run
    attach(slog)
    try:
        comm = _comm()

        # (a) the env spec fires a timeout on the FIRST
        # obj_store.exchange of every process; the lockstep retry
        # absorbs it and records fault_injected + retry on the sink
        got = comm.allgather_obj(pid)
        assert got == list(range(nproc)), got
        assert slog.counts.get("fault_injected", 0) >= 1, slog.counts
        assert slog.counts.get("retry", 0) >= 1, slog.counts

        # (b) trainer with a targeted slow rank.  The delay fault at
        # trainer.update fires only on process 1 (FaultSpec(process=1)),
        # so its per-step host time dominates; MetricsReport allgathers
        # the window summaries and every rank computes the same flags.
        lr = 0.1

        def loss_fn(params, batch):
            return 0.5 * jnp.sum(
                (params["w"] - batch.mean(axis=0)) ** 2
            )

        opt = cmn.create_multi_node_optimizer(optax.sgd(lr), comm)
        step = build_train_step(comm, loss_fn, opt, donate=False)
        params, opt_state = step.place(
            {"w": jnp.zeros((4,))}, opt.init({"w": jnp.zeros((4,))})
        )
        n_local = comm.size // comm.process_count
        rows = np.stack([
            np.full((4,), float(pid * n_local + i), np.float32)
            for i in range(n_local)
        ])
        it = SerialIterator([rows[i] for i in range(n_local)], n_local,
                            shuffle=False)
        trainer = Trainer(Updater(it, step, params, opt_state),
                          stop_trigger=(6, "iteration"))
        rep = obs.MetricsReport(comm, trigger=(3, "iteration"),
                                filename=None)
        trainer.extend(rep)
        trainer.run()
        assert trainer.iteration == 6
        # the LAST window (iterations 4-6) is past both ranks' compile
        # cost: the targeted delay dominates process 1's step mean
        assert rep.straggler_processes == [1], (
            rep.straggler_processes, rep.last_report,
        )

        # (c) process-local eager wire: real multi-device bucket psums
        # within this process's 2 local CPU devices
        local_comm = cmn.create_communicator(
            "tpu", devices=jax.local_devices()
        )
        # two 3 MB leaves: each exceeds what the 4 MiB open bucket
        # could absorb alongside the other -> a 2-bucket plan
        grads = {
            "a": jnp.ones((local_comm.size, 750_000), jnp.float32),
            "b": jnp.ones((local_comm.size, 750_000), jnp.float32),
        }
        local_comm.allreduce_grad(grads)
        psums = tel.timeline.spans("collective.psum")
        assert len(psums) >= 2, len(psums)

        # (d) merge + export + validate
        tel.timeline.merge_resilience(slog)
        tel.timeline.merge_resilience(trainer.resilience_log)  # dedup
        chrome = os.path.join(scratch, f"trace_p{pid}.json")
        jsonl = os.path.join(scratch, f"trace_p{pid}.jsonl")
        tel.timeline.to_chrome_trace(chrome)
        tel.timeline.to_jsonl(jsonl)

        doc = _json.load(open(chrome))
        assert isinstance(doc["traceEvents"], list)
        for e in doc["traceEvents"]:
            assert e["ph"] in ("M", "X", "i"), e
            assert "name" in e and "pid" in e
            if e["ph"] == "X":
                assert e["dur"] >= 0
        rows_out = [_json.loads(l) for l in open(jsonl)]
        ts = [r["t"] for r in rows_out]
        assert ts == sorted(ts), "jsonl not time-ordered"
        names = [r["name"] for r in rows_out]
        assert "step" in names
        assert "collective.psum" in names
        fault_i = names.index("resilience.fault_injected")
        retry_i = names.index("resilience.retry")
        straggler_i = names.index("resilience.straggler")
        assert fault_i < retry_i < straggler_i, (
            fault_i, retry_i, straggler_i,
        )
        # the straggler event names the slow process on every rank
        strag = rows_out[straggler_i]
        assert strag["args"]["process"] == 1, strag
        return {
            "stragglers": rep.straggler_processes,
            "n_events": len(rows_out),
            "n_bucket_psums": len(psums),
            "faults": slog.counts.get("fault_injected", 0),
        }
    finally:
        detach(slog)
        obs.install(None)


def scenario_except_hook(pid, nproc, scratch):
    """Failure containment: process 1 raises; its global except hook
    shuts the distributed client down; process 0, blocked in a KV recv,
    errors out instead of hanging.  BOTH exit non-zero by design."""
    import chainermn_tpu as cmn

    cmn.global_except_hook.add_hook()
    comm = _comm()
    comm.barrier()
    if pid == 1:
        raise RuntimeError("injected failure on process 1")
    # blocks until the (dead) peer's message or the bounded timeout
    # (CHAINERMN_TPU_OBJ_TIMEOUT_MS, set small by the spawning test)
    comm.recv_obj(source=1, tag=99)
    return {}


if __name__ == "__main__":
    main()

"""Convergence-threshold tests: training must actually LEARN.

The reference's real-data examples demonstrated learning for free (an
MNIST run that doesn't learn is visibly broken); the synthetic-data
suite only asserted "loss decreased", which a broken gradient path can
satisfy by luck.  These tests pin each major parallelism tier to a
measurable bar: train the synthetic centroid task (or a deterministic
token task) to >= 0.9 accuracy within a bounded step count on the
8-device mesh.  Ref: SURVEY.md section 2 #33-35, section 4.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import PartitionSpec as P

import chainermn_tpu as cmn
from chainermn_tpu.models import MLP
from chainermn_tpu.utils import SyntheticImageDataset


def _centroid_arrays(n, seed, n_classes=4, shape=(8, 8)):
    ds = SyntheticImageDataset(n, shape=shape, n_classes=n_classes,
                               seed=seed)
    xs = np.stack([ds[i][0] for i in range(n)])
    ys = np.asarray([ds[i][1] for i in range(n)], np.int32)
    return jnp.asarray(xs), jnp.asarray(ys)


def _accuracy(apply_fn, params, x, y):
    logits = apply_fn(params, x)
    return float((jnp.argmax(logits, -1) == y).mean())


class TestDataParallelConverges:
    def test_dp_mlp_reaches_accuracy(self, devices8):
        comm = cmn.create_communicator("tpu", devices=devices8)
        model = MLP(n_units=64, n_out=4, dtype=jnp.float32)
        params = comm.bcast_data(
            model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8)))
        )
        opt = cmn.create_multi_node_optimizer(optax.adam(3e-3), comm)

        def loss_fn(p, b):
            x, y = b
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
        params, opt_state = step.place(params, opt.init(params))

        xtr, ytr = _centroid_arrays(512, seed=0)
        xte, yte = _centroid_arrays(256, seed=7)
        rng = np.random.RandomState(3)
        for _ in range(40):  # bounded: 40 steps of batch 128
            idx = rng.randint(0, 512, 128)
            params, opt_state, _ = step(
                params, opt_state, (xtr[idx], ytr[idx])
            )
        acc = _accuracy(model.apply, jax.device_get(params), xte, yte)
        assert acc >= 0.9, f"DP tier failed to learn: accuracy {acc}"


class _TpClassifier(nn.Module):
    """Replicated embed -> column/row-parallel pair -> logits: the
    hybrid tier's sharded+replicated parameter mix, as a classifier."""

    n_out: int = 4
    model_axis: str = "mn_model"

    @nn.compact
    def __call__(self, x):
        from chainermn_tpu.parallel import (
            ColumnParallelDense,
            RowParallelDense,
        )

        x = x.reshape((x.shape[0], -1))
        x = jnp.tanh(nn.Dense(32, name="embed")(x))
        x = ColumnParallelDense(64, axis_name=self.model_axis)(x)
        x = jax.nn.relu(x)
        return RowParallelDense(self.n_out, axis_name=self.model_axis)(x)


class _DenseClassifier(nn.Module):
    """Init twin: same global weight shapes with plain Dense layers (TP
    modules trace a psum, so they cannot init outside the mesh)."""

    n_out: int = 4

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = jnp.tanh(nn.Dense(32, name="embed")(x))
        x = nn.Dense(64, name="col")(x)
        x = jax.nn.relu(x)
        return nn.Dense(self.n_out, name="row")(x)


class TestHybridConverges:
    def test_hybrid_dp_tp_reaches_accuracy(self, devices8):
        from chainermn_tpu.parallel import megatron_param_specs

        comm = cmn.create_communicator("hybrid", devices=devices8,
                                       tp_size=2)
        model = _TpClassifier()
        dense = _DenseClassifier().init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8, 8))
        )["params"]
        params = {"params": {
            "embed": dense["embed"],
            "ColumnParallelDense_0": dict(dense["col"]),
            "RowParallelDense_0": dict(dense["row"]),
        }}
        specs = megatron_param_specs(params, model_axis="mn_model")
        opt = cmn.create_multi_node_optimizer(optax.adam(3e-3), comm)

        def loss_fn(p, b):
            x, y = b
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        step = cmn.build_train_step(
            comm, loss_fn, opt, data_axes=comm.data_axis_names,
            param_specs=specs, donate=False,
        )
        params, opt_state = step.place(params, opt.init(params))

        xtr, ytr = _centroid_arrays(512, seed=1)
        xte, yte = _centroid_arrays(256, seed=8)
        rng = np.random.RandomState(4)
        for _ in range(40):
            idx = rng.randint(0, 512, 64)
            batch = step.place_batch((xtr[idx], ytr[idx]))
            params, opt_state, _ = step(params, opt_state, batch)

        # evaluate through the same sharded forward
        logits_fn = jax.jit(jax.shard_map(
            lambda p, x: model.apply(p, x),
            mesh=comm.mesh,
            in_specs=(specs, P("mn_data")),
            out_specs=P("mn_data"),
            check_vma=False,
        ))
        logits = logits_fn(
            params, jax.device_put(xte, step.batch_sharding)
        )
        acc = float((jnp.argmax(logits, -1) == yte).mean())
        assert acc >= 0.9, f"hybrid tier failed to learn: accuracy {acc}"


class TestComposedMoeConverges:
    def test_composed_moe_lm_learns_counting(self, devices8):
        """DP x SP x TP x EP composed mesh, trained on a deterministic
        next-token task (tok[t+1] = (tok[t]+1) mod V): >= 0.9 next-token
        accuracy in a bounded step count proves the composed gradient
        path (ring-attention SP, TP collectives, EP dispatch) optimizes,
        not merely runs."""
        from chainermn_tpu.models.moe_transformer import (
            MoeTransformerLM,
            moe_lm_loss,
            moe_param_specs,
        )
        from chainermn_tpu.parallel import sharded_init

        comm = cmn.create_communicator(
            "mesh", devices=devices8, sp_size=2, tp_size=2
        )
        vocab, seq = 16, 16
        model = MoeTransformerLM(
            vocab_size=vocab, d_model=32, n_heads=2, n_layers=2,
            n_experts=2, d_ff=64, moe_every=2, k=1, max_len=seq,
            dtype=jnp.float32, seq_axis="mn_seq", tp_axis="mn_model",
            expert_axis="mn_model",
            aux_stat_axes=("mn_data", "mn_seq", "mn_model"),
        )

        def make_batch(rng, b=16):
            off = rng.randint(0, vocab, (b, 1))
            ramp = np.arange(seq)[None, :]
            return jnp.asarray((off + ramp) % vocab, jnp.int32)

        rng = np.random.RandomState(0)
        params, specs = sharded_init(
            lambda t: model.init(jax.random.PRNGKey(0), t),
            comm.mesh, (P("mn_data", "mn_seq"),),
            moe_param_specs, make_batch(rng),
        )
        opt = cmn.create_multi_node_optimizer(optax.adam(1e-2), comm)

        def loss_fn(p, b):
            return moe_lm_loss(
                model.apply(p, b), b, seq_axis="mn_seq",
                model_axis="mn_model", aux_coef=1e-2,
            )

        step = cmn.build_train_step(
            comm, loss_fn, opt, data_axes=comm.data_axis_names,
            param_specs=specs, batch_specs=P("mn_data", "mn_seq"),
            donate=False,
        )
        params, opt_state = step.place(params, opt.init(params))
        for _ in range(60):
            batch = step.place_batch(make_batch(rng))
            params, opt_state, _ = step(params, opt_state, batch)

        # evaluate through the same sharded forward
        test = make_batch(np.random.RandomState(99))
        fwd = jax.jit(jax.shard_map(
            lambda p, b: model.apply(p, b)[0],
            mesh=comm.mesh,
            in_specs=(specs, P("mn_data", "mn_seq")),
            out_specs=P("mn_data", "mn_seq"),
            check_vma=False,
        ))
        logits = fwd(params, step.place_batch(test))
        pred = np.asarray(jnp.argmax(logits[:, :-1], -1))
        tgt = np.asarray(test[:, 1:])
        acc = float((pred == tgt).mean())
        assert acc >= 0.9, f"composed tier failed to learn: accuracy {acc}"

"""protolint (ISSUE 20): the host-protocol analyzer's three layers.

* the tag registry (``resilience/tags.py``) — disjoint reserved ranges;
* the AST catalog + rules (``analysis/protolint.py``) — synthetic
  fixtures trip each rule, the repo's own catalog is clean;
* the runtime recorder + guard (``resilience/protocol.py`` /
  ``analysis.checks.protocol_agreement``) — including the pinned
  disabled-path contract (one ``is None`` check, shared null context)
  and the FleetReport protocol merge;
* the determinism fixes the lint forced (sorted scans in
  ``serving/replica.py`` and ``extensions/checkpoint.py``), each pinned
  against a reversed-``listdir`` filesystem.

Fast by construction: AST + in-memory recorders, no jax world.
"""

import json
import os
import textwrap

import pytest

from chainermn_tpu.analysis import protolint
from chainermn_tpu.analysis.protolint import (
    build_catalog,
    run_protolint,
    scan_file,
)
from chainermn_tpu.resilience import protocol, tags
from chainermn_tpu.resilience.errors import ProtocolDivergenceError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_recorder():
    """Every test must leave the process-global recorder disabled."""
    yield
    assert protocol.active() is None, "test leaked a ProtocolRecorder"
    protocol.install(None)


def _scan_src(tmp_path, src, name="mod.py"):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return scan_file(str(p), str(tmp_path))


# ----------------------------------------------------------------------
# tag registry
# ----------------------------------------------------------------------
class TestTagRegistry:
    def test_reserved_ranges_are_disjoint(self):
        spans = sorted(
            (r.start, r.stop, r.name) for r in tags.ranges()
        )
        for (s0, e0, n0), (s1, e1, n1) in zip(spans, spans[1:]):
            assert e0 <= s1, f"{n0} overlaps {n1}"

    def test_register_rejects_overlap_and_duplicate(self):
        with pytest.raises(ValueError):
            tags.register("clash", tags.PEER_CKPT_RING, 1)
        with pytest.raises(ValueError):
            tags.register("peer_ckpt.ring", 99999, 1)

    def test_owner_range_resolves_every_registered_tag(self):
        r = tags.owner_range(tags.PEER_CKPT_RING)
        assert r is not None and r.name == "peer_ckpt.ring"
        assert tags.owner_range(tags.DEFAULT).name == "default"
        assert tags.owner_range(10**9) is None

    def test_peer_owner_tag_bounds(self):
        t0 = tags.peer_owner_tag(0)
        assert tags.owner_range(t0).name == "peer_ckpt.restore"
        assert tags.peer_owner_tag(1) == t0 + 1
        with pytest.raises(ValueError):
            tags.peer_owner_tag(tags.MAX_PEER_RESTORE_OWNERS)

    def test_user_tags_are_identity_within_range(self):
        assert tags.user_tag(1) == 1
        assert tags.user_tag(4095) == 4095
        with pytest.raises(ValueError):
            tags.user_tag(0)
        with pytest.raises(ValueError):
            tags.user_tag(4096)


# ----------------------------------------------------------------------
# catalog extraction
# ----------------------------------------------------------------------
class TestCatalogExtraction:
    def test_lockstep_sites_resolved_from_literals_and_constants(
        self, tmp_path
    ):
        sites, _ = _scan_src(tmp_path, """\
            SITE = "my.agree"
            def f(comm):
                lockstep_allgather(comm, 1, site="direct.literal")
                lockstep_allgather(comm, 2, site=SITE)
        """)
        names = {s.site for s in sites if s.kind == "lockstep"}
        assert names == {"direct.literal", "my.agree"}
        assert all(not s.dynamic for s in sites)

    def test_fstring_site_is_dynamic_prefix(self, tmp_path):
        sites, _ = _scan_src(tmp_path, """\
            def f(comm, label):
                lockstep_allgather(comm, 1, site=f"agree({label})")
        """)
        (s,) = [s for s in sites if s.kind == "lockstep"]
        assert s.dynamic and s.site == "agree(*"

    def test_p2p_tags_classified_by_source(self, tmp_path):
        sites, _ = _scan_src(tmp_path, """\
            from chainermn_tpu.resilience.tags import PEER_CKPT_RING
            def f(comm):
                comm.send_obj(1, dest=0)                 # default
                comm.send_obj(1, dest=0, tag=0)          # default
                comm.send_obj(1, dest=0, tag=PEER_CKPT_RING)  # registry
                comm.recv_obj(source=0, tag=9)  # mnlint: allow(proto-magic-tag)
        """)
        srcs = [s.tag_source for s in sites
                if s.kind in ("send", "recv")]
        assert srcs == ["default", "default", "registry", "literal"]

    def test_atomic_write_and_collectives_cataloged(self, tmp_path):
        sites, _ = _scan_src(tmp_path, """\
            import json, os
            def write(doc, path):  # mnlint: allow(proto-adhoc-manifest)
                with open(path + ".tmp", "w") as f:
                    json.dump(doc, f)
                os.replace(path + ".tmp", path)
            def g(comm):
                comm.bcast_obj(1)  # mnlint: allow(x)
        """)
        kinds = {s.kind for s in sites}
        assert "atomic_write" in kinds and "exchange" in kinds


# ----------------------------------------------------------------------
# catalog rules
# ----------------------------------------------------------------------
class TestCatalogRules:
    def test_duplicate_site_flagged_across_files(self, tmp_path):
        for name in ("a.py", "b.py"):
            (tmp_path / name).write_text(
                'def f(c):\n    lockstep_allgather(c, 1, site="dup.x")\n'
            )
        _, violations = run_protolint([str(tmp_path)], str(tmp_path))
        dups = [v for v in violations
                if v.rule == "proto-duplicate-site"]
        assert len(dups) == 2  # flagged at BOTH declaring call sites
        assert all("dup.x" in v.message for v in dups)

    def test_unique_and_dynamic_sites_not_flagged(self, tmp_path):
        (tmp_path / "a.py").write_text(textwrap.dedent("""\
            def f(c, label):
                lockstep_allgather(c, 1, site="only.once")
                lockstep_allgather(c, 1, site=f"per({label})")
                lockstep_allgather(c, 2, site=f"per({label})")
        """))
        _, violations = run_protolint([str(tmp_path)], str(tmp_path))
        assert violations == []

    def test_raw_allgather_flagged_outside_sanctioned(self, tmp_path):
        _, v = _scan_src(tmp_path, """\
            def f(comm):
                return comm.allgather_obj(1)
        """, name="chainermn_tpu/extensions/thing.py")
        assert [x.rule for x in v] == ["proto-raw-allgather"]

    def test_raw_allgather_sanctioned_in_transport(self, tmp_path):
        _, v = _scan_src(tmp_path, """\
            def f(comm):
                return comm.allgather_obj(1)
        """, name="chainermn_tpu/resilience/retry.py")
        assert v == []

    def test_magic_tag_literal_and_arithmetic_flagged(self, tmp_path):
        _, v = _scan_src(tmp_path, """\
            BASE = 7000
            def f(comm, o):
                comm.send_obj(1, dest=0, tag=42)
                comm.send_obj(1, dest=0, tag=BASE + 1 + o)
        """)
        assert [x.rule for x in v] == ["proto-magic-tag"] * 2

    def test_hand_reserved_tag_constant_flagged(self, tmp_path):
        _, v = _scan_src(tmp_path, "PEER_TAG = 7919\n")
        assert [x.rule for x in v] == ["proto-magic-tag"]
        assert "resilience/tags.py" in v[0].message

    def test_registry_resolved_tags_clean(self, tmp_path):
        _, v = _scan_src(tmp_path, """\
            from chainermn_tpu.resilience import tags as _tags
            from chainermn_tpu.resilience.tags import peer_owner_tag
            def f(comm, o):
                comm.send_obj(1, dest=0, tag=peer_owner_tag(o))
                comm.send_obj(1, dest=0, tag=_tags.DEFAULT)
        """)
        assert v == []

    def test_adhoc_manifest_flagged_pickle_exempt(self, tmp_path):
        _, v = _scan_src(tmp_path, """\
            import json, os, pickle
            def bad(doc, path):
                with open(path + ".tmp", "w") as f:
                    json.dump(doc, f)
                os.rename(path + ".tmp", path)
            def binary_commit(obj, path):
                with open(path + ".tmp", "wb") as f:
                    pickle.dump(obj, f)
                os.rename(path + ".tmp", path)
        """)
        assert [x.rule for x in v] == ["proto-adhoc-manifest"]
        assert "bad()" in v[0].message

    def test_manifest_rule_sanctions_elastic(self, tmp_path):
        _, v = _scan_src(tmp_path, """\
            import json, os
            def write_manifest(doc, path):
                with open(path + ".tmp", "w") as f:
                    json.dump(doc, f)
                os.replace(path + ".tmp", path)
        """, name="chainermn_tpu/resilience/elastic.py")
        assert v == []


# ----------------------------------------------------------------------
# the repo's own catalog
# ----------------------------------------------------------------------
class TestRepoCatalog:
    def test_repo_catalog_is_clean(self):
        """Acceptance: the package's host protocol passes every catalog
        rule — unique sites, lockstep-wrapped allgathers, registry
        tags, one manifest writer."""
        _, violations = run_protolint()
        assert violations == [], "\n".join(str(v) for v in violations)

    def test_known_agreement_sites_cataloged_and_unique(self):
        catalog = build_catalog()
        names = catalog.site_names()
        assert len(names) == len(set(names)), names
        for expected in ("evaluator.aggregate", "fleet.rendezvous",
                         "checkpoint.newest_common_step",
                         "peer_ckpt.replicate", "adaptive.agree"):
            assert expected in names, f"{expected} missing from {names}"

    def test_console_entry_is_a_gate(self, tmp_path):
        import subprocess
        import sys

        bad = tmp_path / "offender.py"
        bad.write_text("MY_TAG = 31337\n")
        proc = subprocess.run(
            [sys.executable, "-m", "chainermn_tpu.analysis.protolint",
             str(bad)],
            capture_output=True, text=True, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1
        assert "proto-magic-tag" in proc.stdout


# ----------------------------------------------------------------------
# runtime recorder
# ----------------------------------------------------------------------
class TestRecorder:
    def test_disabled_path_is_the_null_fast_path(self):
        """The pinned zero-overhead contract (telemetry's twin): with
        no recorder installed, the hook is one ``is None`` check and
        the site/asymmetric markers return the SHARED null context —
        no allocation, no lock."""
        assert protocol.active() is None
        protocol.record_op("send", tag=1, peer=0, payload=b"x")
        assert protocol.exchange_site("s") is protocol._NULL
        assert protocol.asymmetric() is protocol._NULL

    def test_obj_store_ops_recorded_with_site_and_digest(self):
        from chainermn_tpu.communicators._obj_store import LocalObjStore

        store = LocalObjStore(size=2)
        with protocol.observe(rank=0, world=2) as rec:
            with protocol.exchange_site("unit.agree"):
                store.allgather("ha")
            store.send("payload", dest=1, tag=5)
            store.recv_for(dest=1, tag=5)
        toks = [e["token"] for e in rec.entries()]
        assert toks[0] == "exchange|unit.agree"
        assert toks[1] == "send|tag=5|peer=+1"
        ents = rec.entries()
        assert ents[1]["digest"] and ents[1]["nbytes"] > 0

    def test_relative_peer_normalization_makes_rings_agree(self):
        sigs = []
        for rank in (0, 1, 2):
            with protocol.observe(rank=rank, world=3) as rec:
                protocol.record_op("send", tag=7, peer=(rank + 1) % 3)
                protocol.record_op("recv", tag=7, peer=(rank - 1) % 3)
            sigs.append(rec.signature())
        assert sigs[0] == sigs[1] == sigs[2]
        assert sigs[0] == ["send|tag=7|peer=+1", "recv|tag=7|peer=+2"]

    def test_asymmetric_ops_logged_but_unsigned(self):
        with protocol.observe(rank=0, world=2) as rec:
            protocol.record_op("send", tag=1, peer=1)
            with protocol.asymmetric():
                protocol.record_op("send", tag=2, peer=1)
        assert len(rec.entries()) == 2
        assert rec.signature() == ["send|tag=1|peer=+1"]
        assert rec.entries()[1]["asymmetric"] is True

    def test_window_advances_on_mark_agreed(self):
        with protocol.observe() as rec:
            protocol.record_op("send", tag=1, peer=0)
            assert len(rec.window_signature()) == 1
            rec.mark_agreed()
            assert rec.window_signature() == []
            protocol.record_op("recv", tag=1, peer=0)
            assert len(rec.window_signature()) == 1

    def test_payload_digest_excluded_from_signature(self):
        sigs = []
        for payload in (b"rank0-data", b"rank1-data"):
            with protocol.observe(rank=0, world=2) as rec:
                protocol.record_op("send", tag=1, peer=1,
                                   payload=payload)
            sigs.append(protocol.signature_hash(rec.signature()))
        assert sigs[0] == sigs[1]

    def test_jsonl_roundtrip(self, tmp_path):
        with protocol.observe(label="x_p0", rank=0, world=2) as rec:
            protocol.record_op("send", tag=3, peer=1, payload=b"z")
        path = rec.to_jsonl(str(tmp_path / "x_p0_protocol.jsonl"))
        rows = [json.loads(l) for l in open(path)]
        assert rows[0]["token"] == "send|tag=3|peer=+1"
        assert rows[0]["seq"] == 0

    def test_env_activation(self, monkeypatch):
        monkeypatch.delenv(protocol.ENV_RECORD, raising=False)
        assert protocol.install_from_env(label="a") is None
        monkeypatch.setenv(protocol.ENV_RECORD, "1")
        rec = protocol.install_from_env(label="a", rank=0, world=2)
        assert rec is protocol.active()
        protocol.install(None)


# ----------------------------------------------------------------------
# the agreement guard
# ----------------------------------------------------------------------
class _FakeComm:
    """lockstep_allgather target: returns this rank's payload plus a
    scripted remote payload."""

    def __init__(self, remote_payloads):
        self.remote = remote_payloads

    def allgather_obj(self, payload):
        return [payload] + list(self.remote)


def _remote_view(sig):
    from chainermn_tpu.resilience.protocol import signature_hash

    return {"hash": signature_hash(sig), "n": len(sig),
            "tail": sig[-8:], "sig": sig}


class TestProtocolAgreement:
    def test_requires_a_recorder(self):
        from chainermn_tpu.analysis import protocol_agreement

        with pytest.raises(RuntimeError, match="PROTOCOL_RECORD"):
            protocol_agreement(_FakeComm([]))

    def test_agreement_passes_and_advances_cursor(self):
        from chainermn_tpu.analysis import protocol_agreement

        with protocol.observe(rank=0, world=2) as rec:
            protocol.record_op("send", tag=1, peer=1)
            mine = rec.window_signature()
            comm = _FakeComm([_remote_view(mine)])
            h = protocol_agreement(comm, label="unit")
        assert h == protocol.signature_hash(mine)
        # cursor advanced past the checked window AND the guard's own
        # (symmetric, but fake here) exchange
        assert rec.window_signature() == []

    def test_divergence_raises_non_recoverable_with_index(self):
        from chainermn_tpu.analysis import protocol_agreement

        with protocol.observe(rank=0, world=2) as rec:
            protocol.record_op("send", tag=1, peer=1)
            protocol.record_op("recv", tag=1, peer=1)
            other = ["send|tag=1|peer=+1", "send|tag=6|peer=+1",
                     "recv|tag=1|peer=+1"]
            comm = _FakeComm([_remote_view(other)])
            with pytest.raises(ProtocolDivergenceError) as ei:
                protocol_agreement(comm, label="unit")
        assert ei.value.recoverable is False
        assert "index 1" in str(ei.value)
        # a FAILED agreement must NOT advance the cursor
        assert len(rec.window_signature()) >= 2

    def test_exported_error_names(self):
        import chainermn_tpu.analysis as ana

        assert ana.ProtocolDivergenceError is ProtocolDivergenceError
        assert callable(ana.protocol_agreement)


# ----------------------------------------------------------------------
# FleetReport protocol merge
# ----------------------------------------------------------------------
class TestFleetReportProtocol:
    def _write(self, scratch, pid, tokens, asym_at=()):
        rows = [
            {"seq": i, "token": t, "asymmetric": i in asym_at}
            for i, t in enumerate(tokens)
        ]
        with open(os.path.join(
            scratch, f"leg0_p{pid}_protocol.jsonl"
        ), "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    def test_agreeing_protocols_report_no_divergence(self, tmp_path):
        from chainermn_tpu.fleet.report import FleetReport

        toks = ["exchange|a", "send|tag=1|peer=+1"]
        self._write(str(tmp_path), 0, toks)
        self._write(str(tmp_path), 1, toks)
        rep = FleetReport.from_scratch(str(tmp_path))
        assert rep.protocol_sequences() == {0: toks, 1: toks}
        assert rep.protocol_divergence() is None

    def test_divergence_pinpoints_first_mismatched_token(self, tmp_path):
        from chainermn_tpu.fleet.report import FleetReport

        self._write(str(tmp_path), 0, ["exchange|a", "exchange|b"])
        self._write(str(tmp_path), 1,
                    ["exchange|a", "exchange|EXTRA", "exchange|b"])
        rep = FleetReport.from_scratch(str(tmp_path))
        div = rep.protocol_divergence()
        assert div == {
            "leg": "leg0", "index": 1,
            "tokens": {0: "exchange|b", 1: "exchange|EXTRA"},
        }
        assert "protocol divergence" in rep.post_mortem()

    def test_asymmetric_rows_excluded_from_comparison(self, tmp_path):
        from chainermn_tpu.fleet.report import FleetReport

        # rank 0 healed a peer (asymmetric send) — NOT a divergence
        self._write(str(tmp_path), 0,
                    ["exchange|a", "send|tag=8000|peer=+1"],
                    asym_at={1})
        self._write(str(tmp_path), 1, ["exchange|a"])
        rep = FleetReport.from_scratch(str(tmp_path))
        assert rep.protocol_divergence() is None


# ----------------------------------------------------------------------
# determinism fixes pinned against a hostile filesystem order
# ----------------------------------------------------------------------
class TestDeterminismFixes:
    def test_journal_scans_invariant_under_listdir_order(
        self, tmp_path, monkeypatch
    ):
        """The spmd-unsorted-scan fixes in serving/replica.py: results
        / draining / handoffs return identical values when listdir
        yields reverse order (two hosts disagreeing on directory order
        must still agree on the scan)."""
        from chainermn_tpu.serving.replica import RequestJournal

        j = RequestJournal(str(tmp_path))
        for i in range(4):
            with open(os.path.join(
                str(tmp_path), f"res_r{i}.json"
            ), "w") as f:
                json.dump({"id": f"r{i}", "state": "done",
                           "tokens": [i]}, f)
            with open(os.path.join(
                str(tmp_path), f"drain_{i}.json"
            ), "w") as f:
                json.dump({}, f)
            open(j.handoff_path(f"r{i}"), "wb").close()

        forward = (j.results(), j.draining(), j.handoffs())
        real = os.listdir
        monkeypatch.setattr(
            os, "listdir",
            lambda p: sorted(real(p), reverse=True),
        )
        assert (j.results(), j.draining(), j.handoffs()) == forward
        assert list(forward[0]) == sorted(forward[0])

    def test_checkpoint_step_inventory_invariant(
        self, tmp_path, monkeypatch
    ):
        """extensions/checkpoint.py:_available_steps feeds
        newest_common_step's cross-rank agreement — the scan must not
        depend on listdir order."""
        from chainermn_tpu.extensions.checkpoint import (
            _MultiNodeCheckpointer,
        )

        ck = object.__new__(_MultiNodeCheckpointer)
        ck._root = str(tmp_path)
        ck._verified = {}
        ck._is_complete = lambda path: True
        for s in (3, 1, 2):
            os.makedirs(os.path.join(str(tmp_path), f"step_{s:012d}"))

        assert ck._available_steps() == [1, 2, 3]
        real = os.listdir
        monkeypatch.setattr(
            os, "listdir",
            lambda p: sorted(real(p), reverse=True),
        )
        assert ck._available_steps() == [1, 2, 3]

#!/usr/bin/env python
"""Benchmark harness: the five BASELINE.md configs, with MFU.

Prints one JSON line per config as it completes, with the HEADLINE line
(ResNet-50 data-parallel, the BASELINE.json primary metric) printed
LAST:

  {"metric": "resnet50_train_images_per_sec_per_chip", "value": ...,
   "unit": "images/sec/chip", "vs_baseline": ..., "step_time_ms": ...,
   "model_tflops_per_step": ..., "mfu": ..., "configs": {...}}

Configs (BASELINE.json):
  1. MNIST MLP data-parallel, flat communicator
  2. ResNet-50 ImageNet data-parallel, hierarchical communicator  [headline]
     (+ a native-C++-input-pipeline variant when a compiler is present)
  3. VGG16 with double-buffering ON vs OFF (the A/B is the point)
  4. ResNet-50 with MultiNodeBatchNormalization (sync-BN over ICI)
  5. seq2seq model-parallel (MultiNodeChainList encoder|decoder)

`vs_baseline` divides by the ChainerMN-era ~125 img/s/chip figure
(BASELINE.md; 1024xP100, 2017 — the only published reference number).
MFU is the auditable calibration: XLA's own per-step FLOP count divided
by (step time x detected chip peak).

Env knobs: BENCH_STEPS (k of the k-in-one-dispatch loop) / BENCH_BATCH
/ BENCH_IMAGE / BENCH_BURN_S / BENCH_ONLY=name,.. / BENCH_SKIP_PROBE /
BENCH_SMOKE=1 (tiny shapes, CPU-friendly smoke run).
"""

import json
import os
import sys

try:  # installed package (pip install -e .)
    import chainermn_tpu  # noqa: F401
except ImportError:  # source checkout: repo root = this file's directory
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CHAINERMN_RESNET50_IMG_PER_SEC_PER_CHIP = 125.0

# Peak bf16 dense FLOP/s per chip by device kind (public figures).
_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

SMOKE = bool(int(os.environ.get("BENCH_SMOKE", "0")))


def _env(name, default):
    return int(os.environ.get(name, default))


def _peak_flops(device):
    override = os.environ.get("BENCH_PEAK_FLOPS")
    if override:
        return float(override)
    kind = getattr(device, "device_kind", "")
    for k, v in _PEAK_BF16.items():
        if kind.startswith(k):
            return v
    return None


def _flops_of(jitted, *args):
    """XLA's own FLOP estimate for one step (honest, auditable)."""
    try:
        analysis = jitted.lower(*args).compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        return float(analysis.get("flops", 0.0)) or None
    except Exception:
        return None


def _flash_attn_tflops(batch, heads, seq, dh, n_layers, causal=True):
    """Analytic attention-matmul FLOPs for one TRAINING step — the term
    XLA's cost analysis cannot see (it treats ``pallas_call`` as a
    black box, so every flash config's XLA count omits the attention
    matmuls entirely; at seq 8192 that is the dominant FLOP term).

    Formula (stated so the number is auditable):
      forward  = QK^T + PV            = 2 matmuls = 4*b*h*s^2*dh FLOPs
      backward = recomputed QK^T + dV/dP/dQ/dK    = 5 matmuls = 2.5x fwd
      training total = 3.5x fwd       = 14*b*h*s^2*dh
      causal: the kernel skips dead blocks        -> halve
    per layer; multiplied by ``n_layers``.
    """
    per_layer = 14.0 * batch * heads * seq * seq * dh
    if causal:
        per_layer /= 2
    return per_layer * n_layers / 1e12


def _fingerprint(**kw):
    """Self-describing config string attached to every bench record so
    cross-round trend lines can't silently compare different models
    (round 2->3 the LM silently went 16h/dh64 -> 8h/dh128)."""
    return "|".join(f"{k}={kw[k]}" for k in sorted(kw))


from chainermn_tpu.utils.benchmarking import (  # noqa: E402
    force_completion as _force,
    protocol_fields as _spread_fields,
    time_kloop as _time_kloop,
    time_steps as _time_steps_raw,
)

# Device burn-in before every timed config: the first executable timed
# in a fresh process under-measures by 20-50% on the tunneled backend
# (see utils/benchmarking.time_steps); ~12s of device activity
# stabilizes it.  BENCH_BURN_S=0 to disable.
_BURN_S = float(os.environ.get("BENCH_BURN_S", "0" if SMOKE else "12"))


# (no _time_steps burn-in wrapper anymore: every live call site invokes
# _time_steps_raw directly with its own burn policy — the native-input
# row burns only its first pass, the seq2seq eager illustration
# deliberately never burns)


def _burned_kloop(run_k, k, repeats=2):
    """Burn-in + paired-k/2k timing of a k-steps-in-one-dispatch
    callable; returns ``(seconds_per_step, samples)`` — the per-repeat
    samples feed every row's min-of-N spread record (round 6: the
    native-input row's ``n_measurements``/``spread_max_over_min``
    protocol extended to ALL rows, VERDICT r5 #1).  The burn loop's
    first call absorbs compilation, then ``_BURN_S`` of device activity
    stabilizes the tunneled backend's decaying per-dispatch cost before
    timing."""
    if _BURN_S > 0:
        import time as _t

        _force(run_k(2))  # compile
        t_end = _t.perf_counter() + _BURN_S
        while _t.perf_counter() < t_end:
            _force(run_k(max(k // 2, 1)))
    return _time_kloop(run_k, k, repeats)


# _spread_fields is utils.benchmarking.protocol_fields (imported above):
# the min-of-N disclosure — n_measurements + spread_max_over_min — is
# ONE protocol defined in one place, shared with every benchmarks/
# script and enforced by analysis.lint's untimed-row rule.


def _copy_spread(dst, src, suffix=""):
    """Propagate one sub-record's spread disclosure into a config row
    (one implementation so no row can silently drop a field; ``suffix``
    distinguishes multi-leg rows like the vgg on/off A/B)."""
    if "n_measurements" in src and "n_measurements" not in dst:
        dst["n_measurements"] = src["n_measurements"]
    if "spread_max_over_min" in src:
        dst["spread_max_over_min" + suffix] = src["spread_max_over_min"]


def _ab_disclosure(rec, leg_a, leg_b, suffix_a, suffix_b):
    """Two-leg A/B row disclosure: total samples across both legs, the
    row spread is the WORSE leg's (the ratio is only as trustworthy as
    its noisier side), then the per-leg fields, suffixed."""
    rec["n_measurements"] = (leg_a.get("n_measurements", 0)
                             + leg_b.get("n_measurements", 0))
    spreads = [r["spread_max_over_min"] for r in (leg_a, leg_b)
               if "spread_max_over_min" in r]
    if spreads:
        rec["spread_max_over_min"] = max(spreads)
    _copy_spread(rec, leg_a, suffix_a)
    _copy_spread(rec, leg_b, suffix_b)


def _kloop_step_time(step, params, opt_state, batch, k, repeats=2):
    """``(seconds_per_step, samples)`` with k steps inside ONE jitted
    fori_loop.

    Round 3/4 found per-dispatch python-loop timing carries +-5-30 %
    tunnel noise even with paired k/2k readbacks (the vgg16_db ratio
    straddled 1.0 across driver captures; sub-ms configs swung 7x) —
    a single dispatch covering k steps is repeatable to ~1 %.  The
    step must be built with ``donate=False`` (the loop re-enters with
    the same buffers)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    if getattr(step, "donate", False):
        raise ValueError(
            "_kloop_step_time requires a step built with donate=False: "
            "the k-loop re-enters with the same buffers, and a donated "
            "step consumes params/opt_state on the warm call"
        )
    inner = step.get_jitted(params, opt_state)

    @jax.jit
    def ksteps(p, o, n):
        def body(i, carry):
            p, o, _ = carry
            p, o, m = inner(p, o, batch)
            return p, o, m["loss"]

        return lax.fori_loop(0, n, body, (p, o, jnp.float32(0)))

    return _burned_kloop(
        lambda n: ksteps(params, opt_state, n)[2], k, repeats
    )


def _train_setup(comm, model, image, batch, n_classes, mutable_bn,
                 double_buffering=False, wire="auto", overlap="none"):
    """Shared scaffolding: params, step fn, a resident synthetic batch."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as cmn

    rng = jax.random.PRNGKey(0)
    variables = model.init(
        rng, jnp.zeros((1, image, image, 3), jnp.bfloat16)
    )
    params = {"params": variables["params"],
              "batch_stats": variables.get("batch_stats", {})}
    params = comm.bcast_data(params)
    opt = cmn.create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm,
        double_buffering=double_buffering, wire=wire, overlap=overlap,
    )

    def loss_fn(p, b):
        x, y = b
        kwargs = {"mutable": ["batch_stats"]} if mutable_bn else {}
        logits = model.apply(
            {"params": p["params"], "batch_stats": p["batch_stats"]},
            x, rngs={"dropout": jax.random.PRNGKey(7)}, **kwargs,
        )
        if mutable_bn:
            logits, _ = logits
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
    params, opt_state = step.place(params, opt.init(params))
    x = jnp.asarray(
        np.random.RandomState(0).randn(batch, image, image, 3), jnp.bfloat16
    )
    y = jnp.asarray(
        np.random.RandomState(1).randint(0, n_classes, (batch,)), jnp.int32
    )
    bx = jax.device_put(x, step.batch_sharding)
    by = jax.device_put(y, step.batch_sharding)

    jitted = step.get_jitted(params, opt_state)
    return step, jitted, (params, opt_state, (bx, by))


def bench_image_model(comm, model, *, image, batch, n_classes=1000,
                      mutable_bn=True, steps=None,
                      double_buffering=False, wire="auto",
                      overlap="none"):
    steps = steps or _env("BENCH_STEPS", 4 if SMOKE else 20)
    step, jitted, args = _train_setup(
        comm, model, image, batch, n_classes, mutable_bn,
        double_buffering=double_buffering, wire=wire, overlap=overlap,
    )
    params, opt_state, batch_dev = args
    step_time, samples = _kloop_step_time(
        step, params, opt_state, batch_dev, steps
    )
    flops = _flops_of(jitted, *args)
    peak = _peak_flops(comm.devices[0])
    out = {
        "images_per_sec": batch / step_time,
        "images_per_sec_per_chip": batch / step_time / comm.size,
        "step_time_ms": step_time * 1e3,
        **_spread_fields(samples),
    }
    if flops:
        out["model_tflops_per_step"] = flops / 1e12
        if peak:
            out["mfu"] = flops / step_time / (peak * comm.size)
    return out


def config_mnist_flat():
    import jax.numpy as jnp

    import chainermn_tpu as cmn
    from chainermn_tpu.models import MLP

    comm = cmn.create_communicator("flat")
    batch = _env("BENCH_MNIST_BATCH", 64 if SMOKE else 2048) * comm.size
    steps = _env("BENCH_STEPS", 4 if SMOKE else 30)

    import jax
    import numpy as np
    import optax

    model = MLP(n_units=1000, dtype=jnp.bfloat16)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))
    params = comm.bcast_data(params)
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.05), comm)

    def loss_fn(p, b):
        x, y = b
        logits = model.apply(p, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
    params, opt_state = step.place(params, opt.init(params))
    x = jnp.asarray(
        np.random.RandomState(0).rand(batch, 28, 28), jnp.float32
    )
    y = jnp.asarray(
        np.random.RandomState(1).randint(0, 10, (batch,)), jnp.int32
    )
    bx = jax.device_put(x, step.batch_sharding)
    by = jax.device_put(y, step.batch_sharding)

    # Sub-ms steps need a BIG k so one dispatch covers the measurement
    # (driver captures ranged 1M-7M samples/s under per-dispatch noise;
    # the k-loop measures 14.9M +-0.2%).
    k = steps * (10 if SMOKE else 100)
    step_time, samples = _kloop_step_time(
        step, params, opt_state, (bx, by), k
    )
    return {
        "metric": "mnist_mlp_flat_samples_per_sec_per_chip",
        "value": round(batch / step_time / comm.size, 2),
        "unit": "samples/sec/chip",
        "step_time_ms": round(step_time * 1e3, 3),
        "communicator": "flat",
        "k_loop": k,
        **_spread_fields(samples),
        "config_fingerprint": _fingerprint(
            arch="mlp1000", b=batch, dtype="bf16"
        ),
    }


def config_resnet50_hierarchical():
    import chainermn_tpu as cmn
    from chainermn_tpu.models import ResNet50, ResNet18

    comm = cmn.create_communicator("hierarchical")
    image = _env("BENCH_IMAGE", 64 if SMOKE else 224)
    batch = _env("BENCH_BATCH", 8 if SMOKE else 128) * comm.size
    model_cls = ResNet18 if SMOKE else ResNet50
    model = model_cls(num_classes=1000, train=True)
    r = bench_image_model(comm, model, image=image, batch=batch)
    per_chip = r["images_per_sec_per_chip"]
    out = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            per_chip / CHAINERMN_RESNET50_IMG_PER_SEC_PER_CHIP, 3
        ),
        "step_time_ms": round(r["step_time_ms"], 2),
        "batch": batch,
        "communicator": "hierarchical",
        "config_fingerprint": _fingerprint(
            arch=model_cls.__name__, b=batch, img=image, bn="bf16"
        ),
    }
    _copy_spread(out, r)
    if "model_tflops_per_step" in r:
        out["model_tflops_per_step"] = round(r["model_tflops_per_step"], 2)
    if "mfu" in r:
        out["mfu"] = round(r["mfu"], 4)
    return out


def _uint8_link_ceiling(dev, batch, image, k=8):
    """SAME-RUN uint8 link-ceiling probe (VERDICT r5 #7): measure the
    H2D bandwidth of exactly the wire payload the native-input config
    ships (a batch of image-size uint8 crops) at the same transport
    instant as the end-to-end number.  The r5 record compared its
    end-to-end draw against a ceiling measured hours earlier on a link
    that drifts 2-6x across a day; recording
    ``fraction_of_link_ceiling`` from a same-run probe removes that
    confound from the committed capture."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    try:
        import h2d_bench
    finally:
        sys.path.pop(0)
    import numpy as np

    rng = np.random.RandomState(0)
    arrs = [
        rng.randint(0, 256, size=(batch, image, image, 3)).astype(np.uint8)
        for _ in range(k)
    ]
    probe = h2d_bench._scalar_probe()
    rtt = h2d_bench.measure_rtt(dev)
    bw = h2d_bench.measure_h2d(dev, probe, arrs, depth=2)
    t_batch = arrs[0].nbytes / bw + rtt
    # component fields merged (**link) into the native-input row, which
    # carries the row-level n_measurements/spread disclosure itself
    # mnlint: allow(untimed-row)
    return {
        "link_uint8_MBps": round(bw / 1e6, 1),
        "link_rtt_ms": round(rtt * 1e3, 2),
        "link_ceiling_img_per_sec_uint8": round(batch / t_batch, 1),
    }


def config_resnet50_native_input():
    """Config 2 variant: the C++ input pipeline feeds real host batches
    (crop/flip off the GIL) instead of a resident device batch — the
    end-to-end number including input.

    uint8 over the wire (VERDICT r4 #2): the loader ships raw uint8
    crops — 1/2 of bf16's bytes, and far more compressible on the
    entropy-sensitive tunnel transport (benchmarks/h2d_bench.py's uint8
    row states the ceiling) — and mean/std/bf16-cast runs INSIDE the
    jitted step (device_normalize fuses into the first conv).  Timing
    is min-of-N (N=3) with the spread reported, because this
    transport-bound config measured 6x run-to-run swings in round 4."""
    from chainermn_tpu.utils.native_loader import (
        NativeImageLoader,
        device_normalize,
        native_available,
    )

    if not native_available():
        return {"metric": "resnet50_native_input", "skipped": "no g++"}

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import ResNet50, ResNet18

    comm = cmn.create_communicator("hierarchical")
    image = _env("BENCH_IMAGE", 64 if SMOKE else 224)
    batch = _env("BENCH_BATCH", 8 if SMOKE else 128) * comm.size
    steps = _env("BENCH_STEPS", 3 if SMOKE else 10)
    n_data = max(batch * 2, 512 if SMOKE else 2048)

    rng = np.random.RandomState(0)
    images = rng.randint(
        0, 256, size=(n_data, image + 8, image + 8, 3), dtype=np.uint8
    )
    labels = rng.randint(0, 1000, size=(n_data,)).astype(np.int32)
    mean, std = (123.7, 116.3, 103.5), (58.4, 57.1, 57.4)
    loader = NativeImageLoader(
        images, labels, batch, crop=(image, image), n_threads=8,
        seed=0, shuffle=True, train=True, mean=mean, std=std,
        wire="uint8",
    )

    model_cls = ResNet18 if SMOKE else ResNet50
    model = model_cls(num_classes=1000, train=True)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3), jnp.bfloat16)
    )
    params = {"params": variables["params"],
              "batch_stats": variables.get("batch_stats", {})}
    params = comm.bcast_data(params)
    opt = cmn.create_multi_node_optimizer(optax.sgd(0.1, momentum=0.9), comm)

    def loss_fn(p, b):
        x_u8, y = b
        x = device_normalize(x_u8, mean, std, dtype=jnp.bfloat16)
        logits, _ = model.apply(
            {"params": p["params"], "batch_stats": p["batch_stats"]},
            x, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    from chainermn_tpu.iterators import prefetch_to_device

    step = cmn.build_train_step(comm, loss_fn, opt)
    params, opt_state = step.place(params, opt.init(params))
    state = {"p": params, "o": opt_state}

    def host_batches():
        while True:
            slot, xv, yv = loader.acquire()
            try:
                # plain copies detach from the zero-copy slot; the wire
                # stays uint8 (half of bf16's bytes, no host-side cast)
                yield (np.array(xv), np.array(yv))
            finally:
                loader.release(slot)

    # double-buffered H2D: batch i+1's device_put is dispatched while
    # step i computes (async dispatch), hiding transfer behind compute
    it = prefetch_to_device(host_batches(), step.place_batch, depth=2)

    def run():
        state["p"], state["o"], m = step(state["p"], state["o"], next(it))
        return m["loss"]

    # min-of-N: first pass carries the burn-in, the rest re-measure the
    # same resident pipeline; the best pass is the number (transport
    # noise only ADDS time) and the spread is reported alongside.
    n_meas = _env("BENCH_NATIVE_REPEATS", 1 if SMOKE else 3)
    dts = []
    try:
        for i in range(n_meas):
            dt_i, _ = _time_steps_raw(
                run, steps, warmup=1, burn_seconds=_BURN_S if i == 0 else 0,
            )
            dts.append(dt_i)
    finally:
        it.close()  # retire the generator's held slot before the loader
        loader.close()
    dt = min(dts)
    # same-run link-ceiling probe; its failure must not kill the row.
    # GLOBAL batch rate vs GLOBAL-batch ceiling (the probe ships the
    # whole batch over the one host link, so the per-chip rate would
    # understate the fraction by comm.size on multi-chip hosts)
    link = {}
    try:
        link = _uint8_link_ceiling(comm.devices[0], batch, image)
        ceiling = link["link_ceiling_img_per_sec_uint8"]
        if ceiling > 0:
            link["fraction_of_link_ceiling"] = round(
                (batch / dt) / ceiling, 3
            )
    except Exception as e:
        link = {"link_ceiling_error": f"{type(e).__name__}: {e}"}
    return {
        "metric": "resnet50_native_input_images_per_sec_per_chip",
        "value": round(batch / dt / comm.size, 2),
        **link,
        "unit": "images/sec/chip (incl. C++ input pipeline, uint8 wire, "
                "double-buffered H2D; min of N)",
        "step_time_ms": round(dt * 1e3, 2),
        "n_measurements": n_meas,
        "spread_max_over_min": round(max(dts) / min(dts), 2),
        "all_images_per_sec_per_chip": [
            round(batch / d / comm.size, 1) for d in dts
        ],
        "config_fingerprint": _fingerprint(
            arch=model_cls.__name__, b=batch, img=image,
            loader="native_cpp", wire="uint8", prefetch=2,
        ),
        "note": (
            "TRANSPORT-BOUND, indicative only: on a tunneled/remote "
            "device the link bandwidth bounds this config and varies "
            "run to run (r4 measured 41-371 img/s across captures of "
            "the bf16-wire variant); uint8 wire halves the bytes and "
            "min-of-N bounds the noise from above — see "
            "docs/performance.md 'Native-input pipeline'"
        ),
    }


def config_vgg16_overlap():
    """Bucket-granularity overlap A/B on VGG (ISSUE 8): the SAME VGG16
    tier timed with the synchronous bucketed wire vs the overlap-
    scheduled program (each bucket's psum issued under the remaining
    backward segments).  This rung REPLACES ``vgg16_db`` — the ROADMAP
    decision rule ("overlap >=1.05x on VGG/ResNet or double-buffering
    retires from bench", executed this round — docs/performance.md
    "Double-buffering: retired from the bench") ended double
    buffering's three captures at ~0.97x; the optimizer class and its
    tests remain.  Both legs are bit-identical programs (same buckets,
    codec, reduction order), so the ratio isolates pure scheduling."""
    import chainermn_tpu as cmn
    from chainermn_tpu.comm_wire import plan_of_tree
    from chainermn_tpu.models import VGG16

    image = _env("BENCH_IMAGE", 64 if SMOKE else 224)
    batch = _env("BENCH_VGG_BATCH", 4 if SMOKE else 64)
    steps = _env("BENCH_STEPS", 3 if SMOKE else 10)
    out = {}
    for mode in ("none", "bucket"):
        comm = cmn.create_communicator("tpu")
        model = VGG16(num_classes=1000, train=True)
        r = bench_image_model(
            comm, model, image=image, batch=batch * comm.size,
            steps=steps, overlap=mode,
        )
        out["on" if mode == "bucket" else "off"] = r
    on, off = out["on"], out["off"]
    import jax

    model = VGG16(num_classes=1000, train=True)
    variables = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, image, image, 3), jax.numpy.bfloat16),
    )
    plan = plan_of_tree(variables)
    rec = {
        "metric": "vgg16_overlap_speedup",
        "value": round(
            on["images_per_sec_per_chip"] / off["images_per_sec_per_chip"],
            3,
        ),
        "unit": "x (bucket overlap ON / OFF; >=1.05x is the gate)",
        "images_per_sec_per_chip_off": round(
            off["images_per_sec_per_chip"], 2
        ),
        "images_per_sec_per_chip_on": round(
            on["images_per_sec_per_chip"], 2
        ),
        "step_time_ms_off": round(off["step_time_ms"], 2),
        "step_time_ms_on": round(on["step_time_ms"], 2),
        "mfu_off": round(off.get("mfu", 0.0), 4) or None,
        "wire_buckets": plan.n_buckets,
        "config_fingerprint": _fingerprint(
            arch="VGG16", b_per_chip=batch, img=image,
            codec="none", buckets=plan.n_buckets, overlap="bucket",
        ),
    }
    _ab_disclosure(rec, off, on, "_off", "_on")
    return rec


def config_grad_wire():
    """Flat-wire gradient-sync A/B (ISSUE 4): the SAME ResNet tier
    timed with the legacy per-leaf psum storm vs the bucketed fused
    wire — the launch-count half of the wire win, on-chip.  The byte
    half (int8) and the sync/dummy split live in
    ``benchmarks/comm_overlap_bench.py``'s ``wire_*`` rungs; this row
    is the driver-captured headline ratio, fingerprinted with the codec
    and bucket count so cross-round trend lines can't silently compare
    different plans."""
    import jax

    import chainermn_tpu as cmn
    from chainermn_tpu.comm_wire import plan_of_tree
    from chainermn_tpu.models import ResNet50, ResNet18

    image = _env("BENCH_IMAGE", 64 if SMOKE else 224)
    batch = _env("BENCH_BATCH", 8 if SMOKE else 128)
    steps = _env("BENCH_STEPS", 3 if SMOKE else 10)
    model_cls = ResNet18 if SMOKE else ResNet50
    out = {}
    for wire in ("per_leaf", "auto"):
        comm = cmn.create_communicator("tpu")
        model = model_cls(num_classes=1000, train=True)
        out[wire] = bench_image_model(
            comm, model, image=image, batch=batch * comm.size,
            steps=steps, wire=wire,
        )
    leaf, bucketed = out["per_leaf"], out["auto"]
    # the plan the "auto" leg compiled — a pure function of shapes, so
    # eval_shape (abstract init, zero device work) is all it needs
    model = model_cls(num_classes=1000, train=True)
    variables = jax.eval_shape(
        model.init, jax.random.PRNGKey(0),
        jax.ShapeDtypeStruct((1, image, image, 3), jax.numpy.float32),
    )
    plan = plan_of_tree(variables)
    rec = {
        "metric": "grad_wire_bucketed_speedup",
        "value": round(
            leaf["step_time_ms"] / bucketed["step_time_ms"], 3
        ),
        "unit": "x (per-leaf step time / bucketed step time)",
        "step_time_ms_per_leaf": round(leaf["step_time_ms"], 2),
        "step_time_ms_bucketed": round(bucketed["step_time_ms"], 2),
        "wire_buckets": plan.n_buckets,
        "wire_n_leaves": plan.n_leaves,
        "config_fingerprint": _fingerprint(
            arch=model_cls.__name__, b_per_chip=batch, img=image,
            codec="none", buckets=plan.n_buckets,
        ),
    }
    _ab_disclosure(rec, leaf, bucketed, "_per_leaf", "_bucketed")
    return rec


def config_resnet50_mnbn():
    import jax.numpy as jnp

    import chainermn_tpu as cmn
    from chainermn_tpu.links.create_mnbn_model import mnbn_factory
    from chainermn_tpu.models import ResNet50, ResNet18

    comm = cmn.create_communicator("tpu")
    image = _env("BENCH_IMAGE", 64 if SMOKE else 224)
    batch = _env("BENCH_BATCH", 8 if SMOKE else 128) * comm.size
    model_cls = ResNet18 if SMOKE else ResNet50
    model = model_cls(
        num_classes=1000, train=True, norm=mnbn_factory(comm),
        dtype=jnp.bfloat16,
    )
    steps = _env("BENCH_STEPS", 3 if SMOKE else 10)
    r = bench_image_model(
        comm, model, image=image, batch=batch, steps=steps,
    )
    out = {
        "metric": "resnet50_mnbn_images_per_sec_per_chip",
        "value": round(r["images_per_sec_per_chip"], 2),
        "unit": "images/sec/chip (sync-BN over ICI)",
        "step_time_ms": round(r["step_time_ms"], 2),
        "config_fingerprint": _fingerprint(
            arch=model_cls.__name__, b=batch, img=image, bn="mnbn_bf16"
        ),
    }
    _copy_spread(out, r)
    if "mfu" in r:
        out["mfu"] = round(r["mfu"], 4)
    return out


def _bench_lm(model, loss_fn, comm, *, batch, seq, vocab,
              with_flops=False, attn_tflops=None):
    """Shared LM-config scaffold: init + broadcast, adamw multi-node
    step, resident token batch, honest paired-run timing.  Returns
    (tokens_per_sec_per_chip, step_time_s, flops_dict).

    ``attn_tflops``: analytic flash-attention FLOPs (TF) to add on top
    of the XLA count (which can't see inside pallas_call); when given,
    the headline ``mfu`` includes it and the XLA-only figure is kept as
    ``mfu_xla_counted``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as cmn

    steps = _env("BENCH_STEPS", 3 if SMOKE else 10)
    toks0 = jnp.zeros((1, seq), jnp.int32)
    params = comm.bcast_data(model.init(jax.random.PRNGKey(0), toks0))
    opt = cmn.create_multi_node_optimizer(
        optax.adamw(3e-4, weight_decay=0.01), comm
    )
    step = cmn.build_train_step(comm, loss_fn, opt, donate=False)
    params, opt_state = step.place(params, opt.init(params))
    toks = jnp.asarray(
        np.random.RandomState(0).randint(0, vocab, (batch, seq)), jnp.int32
    )
    bt = jax.device_put(toks, step.batch_sharding)
    step_time, samples = _kloop_step_time(step, params, opt_state, bt,
                                          steps)
    extra = _spread_fields(samples)
    if with_flops:
        flops = _flops_of(
            step.get_jitted(params, opt_state), params, opt_state, bt
        )
        peak = _peak_flops(comm.devices[0])
        if flops:
            total = flops + (attn_tflops or 0.0) * 1e12
            extra["model_tflops_per_step"] = round(total / 1e12, 2)
            if attn_tflops:
                extra["attn_tflops_analytic"] = round(attn_tflops, 2)
                extra["tflops_xla_counted"] = round(flops / 1e12, 2)
            if peak:
                extra["mfu"] = round(
                    total / step_time / (peak * comm.size), 4
                )
                if attn_tflops:
                    extra["mfu_xla_counted"] = round(
                        flops / step_time / (peak * comm.size), 4
                    )
    tps = batch * seq / step_time / comm.size
    return tps, step_time, extra


def _lm_dims():
    vocab = 2048 if SMOKE else 32768
    d_model = 128 if SMOKE else 1024
    n_layers = 2 if SMOKE else 8
    return vocab, d_model, n_layers


def _lm_heads(d_model):
    """Head width 128 = the MXU lane dimension: dh=64 leaves half the
    lanes idle in the flash kernel's QK/PV matmuls — measured 40%
    slower end-to-end (benchmarks/transformer_mfu.py heads8 rung)."""
    return max(d_model // 128, 1)


def config_transformer_lm():
    """Beyond the reference's workloads: decoder-only LM with the Pallas
    flash-attention kernel — the matmul-heavy config where MFU should
    approach the chip's practical ceiling."""
    import chainermn_tpu as cmn
    from chainermn_tpu.models.transformer import TransformerLM, lm_loss
    from chainermn_tpu.ops.pallas_attention import flash_attention_fn

    comm = cmn.create_communicator("tpu")
    vocab, d_model, n_layers = _lm_dims()
    seq = 128 if SMOKE else 2048
    batch = _env("BENCH_LM_BATCH", 2 if SMOKE else 8) * comm.size
    heads = _lm_heads(d_model)
    # Split fwd/bwd flash geometry (round-5 sweep, confirmed twice in
    # swapped order): fwd 1024x2048 + bwd 1024x1024 measures 120.3/
    # 120.9 ms vs 123.2/123.4 shared — +2% at seq 2048 (the backward's
    # scoped-VMEM limit does not bind the forward).  seq 8192 prefers
    # shared 1024x1024 (its config below keeps it).
    fbq, fbk, bbq, bbk = 1024, 2048, 1024, 1024
    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, n_heads=heads,
        n_layers=n_layers, max_len=seq,
        attention_fn=None if SMOKE else flash_attention_fn(
            block_q=fbq, block_k=fbk,
            bwd_block_q=bbq, bwd_block_k=bbk,
        ),
    )
    attn = None if SMOKE else _flash_attn_tflops(
        batch, heads, seq, d_model // heads, n_layers
    )
    tps, step_time, extra = _bench_lm(
        model, lambda p, b: lm_loss(model.apply(p, b), b), comm,
        batch=batch, seq=seq, vocab=vocab, with_flops=True,
        attn_tflops=attn,
    )
    return {
        "metric": "transformer_lm_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip (flash attention, bf16)",
        "step_time_ms": round(step_time * 1e3, 2),
        "seq_len": seq,
        "d_model": d_model,
        "n_layers": n_layers,
        "n_heads": model.n_heads,
        "config_fingerprint": _fingerprint(
            arch="dense_lm", b=batch, s=seq, d=d_model, L=n_layers,
            h=heads, v=vocab,
            # derived from the SAME variables passed to the kernel so a
            # retune cannot silently desynchronize the recorded geometry
            # ("split" = the round-6 diagonal-split taxonomy)
            attn=(f"flash_split_f{fbq}x{fbk}_b{bbq}x{bbk}"
                  if not SMOKE else "xla"),
        ),
        **extra,
    }


def _long_seq_lm_config(*, seq, smoke_seq, batch_env, batch_default):
    """Shared body of the long-sequence LM tiers (seq 8192 / 16384):
    identical model, 1024x1024 flash blocks (the r4/r5 sweeps' choice
    at both lengths), analytic attention FLOPs and fingerprint — only
    the length, batch knob and metric strings differ, so a fix to one
    tier cannot miss the other."""
    import chainermn_tpu as cmn
    from chainermn_tpu.models.transformer import TransformerLM, lm_loss
    from chainermn_tpu.ops.pallas_attention import flash_attention_fn

    comm = cmn.create_communicator("tpu")
    vocab, d_model, n_layers = _lm_dims()
    s = smoke_seq if SMOKE else seq
    batch = _env(batch_env, batch_default) * comm.size
    heads = _lm_heads(d_model)
    model = TransformerLM(
        vocab_size=vocab, d_model=d_model, n_heads=heads,
        n_layers=n_layers, max_len=s,
        attention_fn=None if SMOKE else flash_attention_fn(
            block_q=1024, block_k=1024
        ),
    )
    attn = None if SMOKE else _flash_attn_tflops(
        batch, heads, s, d_model // heads, n_layers
    )
    tps, step_time, extra = _bench_lm(
        model, lambda p, b: lm_loss(model.apply(p, b), b), comm,
        batch=batch, seq=s, vocab=vocab, with_flops=True,
        attn_tflops=attn,
    )
    return {
        "metric": f"transformer_lm_seq{seq}_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": f"tokens/sec/chip (flash attention, bf16, seq {seq})",
        "step_time_ms": round(step_time * 1e3, 2),
        "seq_len": s,
        "config_fingerprint": _fingerprint(
            arch="dense_lm", b=batch, s=s, d=d_model, L=n_layers,
            h=heads, v=vocab,
            attn="flash_split_1024x1024" if not SMOKE else "xla",
        ),
        **extra,
    }


def config_transformer_lm_long():
    """Long-context tier: seq 8192 where XLA's fused attention OOMs on
    this chip — the flash kernel is what makes the config exist at all
    (docs/performance.md).  Batch 2 with 1024x1024 flash blocks: the
    round-4 sweep (benchmarks/longseq_tune.py) measured 94.3k tok/s
    (MFU 0.61) there vs 67.8k at the round-3 defaults (b1, 256x512
    blocks, which were tuned at seq 2048); 1024x2048 blocks exceed the
    16 MB scoped-vmem limit and b4 OOMs HBM."""
    return _long_seq_lm_config(seq=8192, smoke_seq=256,
                               batch_env="BENCH_LM_LONG_BATCH",
                               batch_default=2)


def config_transformer_lm_xl():
    """seq-16384 tier, promoted to a first-class fingerprinted config
    (VERDICT r5 #4: the 61.3k tok/s round-5 result lived only in the
    perf doc's prose — a regression there was invisible to the bench).
    Batch 1, 1024x1024 flash blocks (the r5 sweep's choice at this
    length); attention is ~72% of the analytic FLOPs here, and under
    the diagonal-split kernel 120 of 136 live blocks per program run
    the unmasked fast branch (block_census) — the config where the
    split's win is largest."""
    return _long_seq_lm_config(seq=16384, smoke_seq=512,
                               batch_env="BENCH_LM_XL_BATCH",
                               batch_default=1)


def config_moe_lm():
    """MoE tier: GShard-style top-2 routed experts every other block
    (models/moe_transformer.py) — measures the routing + expert-compute
    machinery; on one chip the expert exchange degenerates (the EP
    all_to_all path is exercised by tests and dryrun_multichip)."""
    import chainermn_tpu as cmn
    from chainermn_tpu.models.moe_transformer import (
        MoeTransformerLM,
        moe_lm_loss,
    )
    from chainermn_tpu.ops.pallas_attention import flash_attention_fn

    comm = cmn.create_communicator("tpu")
    vocab, d_model, n_layers = _lm_dims()
    n_experts = 4 if SMOKE else 8
    seq = 128 if SMOKE else 2048
    # batch 4/chip: the round-5 sweep measured 86.0k tok/s vs 80.6k at
    # b2 and 80.9k at b8 (b8 posts the highest MFU, 0.564, but pays
    # ~13% more routed-capacity FLOPs per token — tokens/s is the
    # user-facing number, so b4 is the default)
    batch = _env("BENCH_MOE_BATCH", 2 if SMOKE else 4) * comm.size
    heads = _lm_heads(d_model)
    model = MoeTransformerLM(
        vocab_size=vocab, d_model=d_model, n_heads=heads,
        n_layers=n_layers, n_experts=n_experts, moe_every=2, k=2,
        max_len=seq,
        dispatch_impl=os.environ.get("BENCH_MOE_DISPATCH", "auto"),
        attention_fn=None if SMOKE else flash_attention_fn(),
    )
    attn = None if SMOKE else _flash_attn_tflops(
        batch, heads, seq, d_model // heads, n_layers
    )
    tps, step_time, extra = _bench_lm(
        model,
        lambda p, b: moe_lm_loss(model.apply(p, b), b, aux_coef=1e-2),
        comm, batch=batch, seq=seq, vocab=vocab, with_flops=True,
        attn_tflops=attn,
    )
    return {
        "metric": "moe_lm_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/sec/chip (top-2 MoE every other block)",
        "step_time_ms": round(step_time * 1e3, 2),
        "n_experts": n_experts,
        "config_fingerprint": _fingerprint(
            arch="moe_lm", b=batch, s=seq, d=d_model, L=n_layers,
            h=heads, v=vocab, E=n_experts, k=2, every=2,
            attn="flash_split" if not SMOKE else "xla",
        ),
        **extra,
    }


def config_seq2seq_mp():
    """Seq2seq model-parallel — re-expressed honestly (VERDICT r4 #4).

    Three measurements, each named for what it is:
    1. the one-chip WHOLE-STEP-JITTED chain (both stages share the only
       chip — a dispatch-cost number, so NO MFU field: the placement is
       degenerate and an MFU would imply a model-parallel efficiency
       this config cannot measure);
    2. the chain's native eager per-stage dispatch (the reference's
       fill-drain ergonomics) — the cost whole-step jit removes;
    3. the same enc|dec split through the REAL pipeline tier
       (parallel.build_pipeline_train_step, 2 stages, GPipe) in a CPU
       virtual-mesh subprocess — a structure/convergence record (twin
       equality is pinned by tests/test_parallel.py), not a TPU number.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.link import MultiNodeChainList

    comm = cmn.create_communicator("tpu")
    vocab = 1024 if SMOKE else 8192
    units = 128 if SMOKE else 512
    seqlen = 16 if SMOKE else 40
    batch = _env("BENCH_SEQ_BATCH", 8 if SMOKE else 64)
    steps = _env("BENCH_STEPS", 3 if SMOKE else 10)

    # encoder on rank 0 / decoder on rank min(1, size-1): the reference's
    # seq2seq_mp1 split (both land on the same chip in a 1-chip world).
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "examples", "seq2seq"),
    )
    from seq2seq_mp1 import DecoderStage, EncoderStage

    model = MultiNodeChainList(comm)
    dec_rank = min(1, comm.size - 1)
    model.add_link(EncoderStage(vocab, units, 2), rank_in=None,
                   rank_out=dec_rank, rank=0)
    model.add_link(DecoderStage(vocab, units, 2), rank_in=[0, None],
                   rank_out=None, rank=dec_rank)

    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.randint(1, vocab, (batch, seqlen)), jnp.int32)
    tgt = jnp.asarray(rng.randint(1, vocab, (batch, seqlen)), jnp.int32)

    params = model.init(jax.random.PRNGKey(0), (src, tgt))

    def loss_fn(logits, tgt):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], tgt[:, 1:]
        ).mean()

    vag = model.value_and_grad(loss_fn)
    opt = model.optimizer(optax.adam(1e-3))
    state = opt.init(params)

    # One compiled program for the whole multi-stage step: the chain's
    # stage-by-stage dispatch (its eager ergonomics) would otherwise pay
    # one host round-trip per op, which a high-latency link amplifies.
    import jax as _jax

    @_jax.jit
    def whole_step(params, state):
        loss, grads = vag(params, (src, tgt), tgt)
        params, state = opt.update(grads, state, params)
        return params, state, loss

    # k whole-steps in one dispatch (same noise-proofing as the other
    # configs; this config's ~5 ms steps drowned in dispatch noise —
    # r03/r04 captures differed 35%)
    @_jax.jit
    def ksteps(p, s, n):
        def body(i, carry):
            p, s, _ = carry
            return whole_step(p, s)

        return _jax.lax.fori_loop(
            0, n, body, (p, s, jnp.float32(0))
        )

    k = steps * (2 if SMOKE else 10)
    step_time, kloop_samples = _burned_kloop(
        lambda n: ksteps(params, state, n)[2], k
    )
    tokens = batch * seqlen * 2  # enc + dec

    # 2. eager per-stage dispatch (the chain's ergonomic tier): each
    # stage + the optimizer dispatches separately, paying the link RTT
    # per dispatch — the cost the whole-step jit removes.  Few steps,
    # no burn: this is an illustration of dispatch overhead (+-20 % is
    # fine), not a throughput claim.
    def eager_run():
        nonlocal params, state
        loss, grads = vag(params, (src, tgt), tgt)
        params, state = opt.update(grads, state, params)
        return loss

    eager_dt, _ = _time_steps_raw(eager_run, 2 if SMOKE else 3, warmup=1)

    # 3. the REAL pipeline: enc|dec through build_pipeline_train_step
    # on a CPU virtual mesh in a subprocess (it must never touch the
    # TPU this process holds; the script forces the cpu platform
    # before any backend query).
    pipeline_rec = None
    if not SMOKE:
        import subprocess

        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        # append, not clobber: the operator's XLA_FLAGS may be load-
        # bearing for their XLA install
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2"
        ).strip()
        try:
            r = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "benchmarks", "pipeline_seq2seq.py"),
                 "--steps", "8", "--batch", str(batch),
                 "--unit", str(units), "--seqlen", str(seqlen),
                 "--vocab", str(vocab)],
                capture_output=True, text=True, timeout=600, env=env,
            )
            lines = r.stdout.strip().splitlines()
            if r.returncode != 0 or not lines:
                pipeline_rec = {
                    "error": f"exit {r.returncode}: "
                             f"{(r.stderr or r.stdout)[-300:]}"
                }
            else:
                pipeline_rec = json.loads(lines[-1])
        except Exception as e:
            pipeline_rec = {"error": f"{type(e).__name__}: {e}"}

    out = {
        "metric": "seq2seq_mp_tokens_per_sec_per_chip",
        "value": round(tokens / step_time / comm.size, 1),
        "unit": "tokens/sec/chip (enc|dec chain, WHOLE step jitted, "
                "both stages on the ONE chip - a dispatch-cost "
                "measurement, not a pipeline)",
        "step_time_ms": round(step_time * 1e3, 2),
        **_spread_fields(kloop_samples),
        "eager_per_stage_step_ms": round(eager_dt * 1e3, 1),
        "eager_vs_jit_dispatch_cost_x": round(eager_dt / step_time, 1),
        "pipeline_2stage_virtual_mesh": pipeline_rec,
        "n_chips": comm.size,
        "config_fingerprint": _fingerprint(
            arch="seq2seq_gru2", b=batch, s=seqlen, units=units, v=vocab
        ),
    }
    return out


def _probe_device(timeout_s: int):
    """Backend reachability probe in a SUBPROCESS.

    When the tunneled TPU's relay dies, any `jax.devices()` call blocks
    indefinitely inside the PJRT client (a C call — even SIGALRM can't
    interrupt it), so a wedged tunnel would leave the whole bench hung
    with zero output and the driver would capture nothing.  A subprocess
    probe can be killed from outside; on failure the harness emits a
    parseable error record instead of hanging.  Returns None on
    success, else a human-readable failure description (a fast non-zero
    exit is a backend/install error, NOT a tunnel timeout — the two
    need different debugging)."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return (
            f"probe timed out after {timeout_s}s — tunneled device "
            "relay down / claim unreleased?"
        )
    if r.returncode != 0:
        return (
            f"probe exited {r.returncode} (backend init error, not a "
            f"timeout): {r.stderr.strip()[-500:]}"
        )
    return None


def main():
    headline = None
    extras = {}
    if not SMOKE and not bool(int(os.environ.get("BENCH_SKIP_PROBE",
                                                 "0"))):
        probe_s = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "240"))
        failure = _probe_device(probe_s)
        if failure:
            print(json.dumps({
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": None,
                "unit": "images/sec/chip",
                "vs_baseline": None,
                "error": (
                    f"device backend unreachable: {failure}; see "
                    "BENCH_r04_local.json for the committed local "
                    "capture of this revision"
                ),
            }), flush=True)
            return
    secondary = [
        ("mnist", config_mnist_flat),
        ("vgg16_overlap", config_vgg16_overlap),
        ("grad_wire", config_grad_wire),
        ("resnet50_mnbn", config_resnet50_mnbn),
        ("transformer_lm", config_transformer_lm),
        ("transformer_lm_long", config_transformer_lm_long),
        ("transformer_lm_xl", config_transformer_lm_xl),
        ("moe_lm", config_moe_lm),
        ("seq2seq_mp", config_seq2seq_mp),
        ("resnet50_native_input", config_resnet50_native_input),
    ]
    only = os.environ.get("BENCH_ONLY")  # comma-separated config names
    if only:
        names = {n.strip() for n in only.split(",")}
        secondary = [(n, f) for n, f in secondary if n in names]
        if "resnet50" not in names and "headline" not in names:
            secondary_only = True
        else:
            secondary_only = False
    else:
        secondary_only = False
    try:
        try:
            if not secondary_only:
                headline = config_resnet50_hierarchical()
        except Exception as e:  # secondaries must still run
            headline = {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": None,
                "unit": "images/sec/chip",
                "vs_baseline": None,
                "error": f"{type(e).__name__}: {e}",
            }
        for name, fn in secondary:
            try:
                r = fn()
            except Exception as e:  # keep the harness alive per config
                r = {"metric": name, "error": f"{type(e).__name__}: {e}"}
            extras[name] = r
            print(json.dumps(r), flush=True)
    finally:
        if headline is None:
            headline = {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": None,
                "unit": "images/sec/chip",
                "vs_baseline": None,
                "error": (
                    "headline filtered out by BENCH_ONLY" if only
                    else "headline config failed"
                ),
            }
        # Full record -> file (the driver's capture keeps only the LAST
        # ~2000 chars of stdout: round 3's final line embedded the whole
        # configs dict, blew that budget, and the driver recorded
        # parsed=null.  The final printed line now stays compact —
        # value+MFU per config — so it always survives the tail window.)
        full = dict(headline)
        full["configs"] = {
            k: {kk: vv for kk, vv in v.items() if kk != "configs"}
            for k, v in extras.items()
        }
        if not only:  # a filtered run must not clobber the full capture
            try:
                with open(
                    os.path.join(
                        os.path.dirname(os.path.abspath(__file__)),
                        "bench_out.json"), "w"
                ) as f:
                    json.dump(full, f, indent=1)
            except OSError:
                pass
        # compact VIEW of rows already captured (with their protocol
        # fields) in bench_out.json — not a measurement row
        headline["summary"] = {  # mnlint: allow(untimed-row)
            k: {
                "v": v.get("value"),
                "mfu": v.get("mfu"),
                "mfu_x": v.get("mfu_xla_counted"),
                "ms": v.get("step_time_ms"),
                "u": v.get("unit"),
            }
            for k, v in extras.items()
        }
        line = json.dumps(headline)
        if len(line) > 1900:  # driver keeps only the last ~2000 chars
            for s in headline["summary"].values():
                s.pop("u", None)
            line = json.dumps(headline)
        print(line, flush=True)


if __name__ == "__main__":
    main()

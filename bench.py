#!/usr/bin/env python
"""Headline benchmark: ResNet-50 data-parallel training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): ChainerMN's published ResNet-50/ImageNet runs work
out to ~125 images/sec/chip (1024 P100s, 90 epochs in 15 min ≈ 128k img/s
total).  The north star is matching/beating per-chip throughput with ≥90 %
scaling efficiency; on one attached chip we measure images/sec/chip for the
full train step (fwd+bwd+update, bf16, global-batch-sharded input).
"""

import json
import os
import sys
import time

try:  # installed package (pip install -e .)
    import chainermn_tpu  # noqa: F401
except ImportError:  # source checkout: repo root = this file's directory
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CHAINERMN_RESNET50_IMG_PER_SEC_PER_CHIP = 125.0


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import chainermn_tpu as cmn
    from chainermn_tpu.models import ResNet50

    devices = jax.devices()
    comm = cmn.create_communicator("tpu", devices=devices)

    batch = int(os.environ.get("BENCH_BATCH", 128)) * comm.size
    image = int(os.environ.get("BENCH_IMAGE", 224))
    steps = int(os.environ.get("BENCH_STEPS", 20))
    warmup = int(os.environ.get("BENCH_WARMUP", 5))

    model = ResNet50(num_classes=1000, train=True)
    rng = jax.random.PRNGKey(0)
    variables = model.init(rng, jnp.zeros((1, image, image, 3), jnp.bfloat16))
    params = {"params": variables["params"],
              "batch_stats": variables.get("batch_stats", {})}
    params = comm.bcast_data(params)

    opt = cmn.create_multi_node_optimizer(
        optax.sgd(0.1, momentum=0.9), comm
    )

    def loss_fn(p, b):
        x, y = b
        logits, mut = model.apply(
            {"params": p["params"], "batch_stats": p["batch_stats"]},
            x, mutable=["batch_stats"],
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).mean()

    step = cmn.build_train_step(comm, loss_fn, opt)

    opt_state = opt.init(params)
    params, opt_state = step.place(params, opt_state)

    x = jnp.asarray(
        np.random.RandomState(0).randn(batch, image, image, 3),
        jnp.bfloat16,
    )
    y = jnp.asarray(
        np.random.RandomState(1).randint(0, 1000, size=(batch,)), jnp.int32
    )
    bx = jax.device_put(x, step.batch_sharding)
    by = jax.device_put(y, step.batch_sharding)

    for _ in range(warmup):
        params, opt_state, m = step(params, opt_state, (bx, by))
    jax.block_until_ready(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, m = step(params, opt_state, (bx, by))
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0

    img_per_sec = batch * steps / dt
    per_chip = img_per_sec / comm.size
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            per_chip / CHAINERMN_RESNET50_IMG_PER_SEC_PER_CHIP, 3
        ),
    }))


if __name__ == "__main__":
    main()
